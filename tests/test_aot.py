"""Instant-start tests: AOT step compilation + the persistent cache.

The acceptance contract: an AOT-dispatched step must be *bitwise*
equal to the plain jit path for every signature in the bucket ladder,
unseen shapes must fall back (counted) rather than fail, the keyed
manifest must read warm-vs-cold correctly, and ``TrainDriver.build``
must stamp the startup clocks the ``live_start`` bench row reports.
"""

import os

import numpy as np
import optax
import pytest

from blendjax.data import bucket_sizes, pad_to_bucket
from blendjax.models import CubeRegressor
from blendjax.train import (
    TrainDriver,
    make_supervised_step,
    make_train_state,
)
from blendjax.train.aot import (
    AotStepSet,
    batch_specs_for_ladder,
    build_aot_step,
    cache_key,
)
from blendjax.utils.metrics import metrics

B, HW = 8, 16


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    metrics.reset()


def _counters():
    return metrics.report()["counters"]


def _batch(n=B, seed=7):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.integers(0, 255, (n, HW, HW, 4), np.uint8),
        "xy": (rng.random((n, 8, 2)) * HW).astype(np.float32),
    }


def _state(batch):
    return make_train_state(
        CubeRegressor(), batch["image"], optimizer=optax.sgd(0.01)
    )


# -- ladder derivation --------------------------------------------------------


def test_batch_specs_cover_full_batch_and_masked_ladder():
    specs = batch_specs_for_ladder(_batch())
    # steady state first: full batch, no mask
    assert "_mask" not in specs[0]
    assert specs[0]["image"].shape == (B, HW, HW, 4)
    assert specs[0]["xy"].dtype == np.float32
    # then every pad_to_bucket size, each with its f32 mask
    ladder = [s["image"].shape[0] for s in specs[1:]]
    assert tuple(ladder) == bucket_sizes(B)
    for s in specs[1:]:
        assert s["_mask"].dtype == np.float32
        assert s["_mask"].shape == (s["image"].shape[0],)


def test_batch_specs_ignore_stamps_and_scalars():
    batch = {**_batch(), "_seq": 3, "frameid": 9, "_trace": {"t": 1}}
    specs = batch_specs_for_ladder(batch)
    assert set(specs[0]) == {"image", "xy"}


def test_batch_specs_honor_explicit_buckets():
    specs = batch_specs_for_ladder(_batch(), buckets=(2, 8))
    assert [s["image"].shape[0] for s in specs] == [B, 2, 8]


def test_batch_specs_require_array_fields():
    with pytest.raises(ValueError):
        batch_specs_for_ladder({"frameid": 3, "_seq": 0})


def test_batch_specs_carry_committed_sharding():
    """A mesh run's example batch arrives sharded over the data axis;
    the ladder specs must keep that sharding — an executable lowered
    against a replicated batch is a DIFFERENT program (no grad-sync
    collectives) and rejects the live sharded layout at dispatch."""
    import jax

    from blendjax.parallel import batch_sharding, create_mesh

    import numpy as _np

    mesh = create_mesh({"data": -1})  # conftest forces 8 CPU devices
    sharded = {
        k: jax.device_put(v, batch_sharding(mesh))
        for k, v in _batch().items()
    }
    n_dev = int(_np.prod(tuple(mesh.devices.shape)))
    specs = batch_specs_for_ladder(sharded, buckets=(B, 4))
    assert specs[0]["image"].sharding == sharded["image"].sharding
    # a bucket the mesh still divides keeps the sharding (B == lead)
    assert specs[1]["image"].sharding == sharded["image"].sharding
    # a bucket the mesh can NOT divide (4 over 8 devices) drops it
    # rather than compiling an executable no real batch could feed
    if 4 % n_dev:
        assert specs[2]["image"].sharding is None
    # numpy example batches lower exactly as before: no sharding
    plain = batch_specs_for_ladder(_batch(), buckets=(4,))
    assert plain[0]["image"].sharding is None


# -- AOT-vs-eager equality ----------------------------------------------------


def test_aot_vs_eager_bitwise_loss_equality_across_ladder():
    """Every dispatchable signature — the full batch plus each padded
    bucket — must produce the identical f32 loss and identical params
    through the precompiled executable and the plain jit."""
    full = _batch()
    state = _state(full)
    aot = build_aot_step(make_supervised_step(donate=False), state, full)
    ref_step = make_supervised_step(donate=False)

    cases = [dict(full)]
    for n in (1, 2, 3, 5, 7):
        cases.append(pad_to_bucket(
            {"image": full["image"][:n], "xy": full["xy"][:n],
             "_partial": True},
            batch_size=B,
        ))

    for batch in cases:
        s_aot, m_aot = aot(state, dict(batch))
        s_ref, m_ref = ref_step(
            state, {k: v for k, v in batch.items()
                    if k == "_mask" or not k.startswith("_")},
        )
        assert float(m_aot["loss"]) == float(m_ref["loss"])  # bitwise
        import jax

        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            s_aot.params, s_ref.params,
        )
    # the whole ladder dispatched through precompiled executables
    assert _counters().get("train.aot_fallbacks") is None
    assert len(aot.signatures) == 1 + len(bucket_sizes(B))


def test_aot_unseen_shape_falls_back_and_counts():
    full = _batch()
    state = _state(full)
    aot = build_aot_step(make_supervised_step(donate=False), state, full)
    odd = _batch(n=3)  # lead 3, unmasked: not a ladder signature
    _, m = aot(state, odd)
    assert np.isfinite(float(m["loss"]))
    assert _counters().get("train.aot_fallbacks") == 1


def test_aot_compile_span_recorded():
    full = _batch()
    state = _state(full)
    build_aot_step(make_supervised_step(donate=False), state, full,
                   buckets=(8,))
    spans = metrics.report()["spans"]
    assert spans["train.compile_ms"]["count"] == 1
    assert spans["train.compile_ms"]["total_s"] > 0


# -- persistent cache manifest ------------------------------------------------


@pytest.fixture
def _cache_config_guard():
    """configure_compilation_cache mutates process-global jax.config (by
    design — it is a process-level lever); restore it so the rest of the
    suite compiles exactly as it would without these tests."""
    import jax

    keys = (
        "jax_compilation_cache_dir",
        "jax_persistent_cache_min_compile_time_secs",
        "jax_persistent_cache_min_entry_size_bytes",
        "jax_persistent_cache_enable_xla_caches",
    )
    saved = {}
    for k in keys:
        try:
            saved[k] = getattr(jax.config, k)
        except AttributeError:
            pass
    yield
    for k, v in saved.items():
        try:
            jax.config.update(k, v)
        except Exception:
            pass
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


@pytest.mark.usefixtures("_cache_config_guard")
def test_manifest_cold_then_warm_counters(tmp_path):
    cache = str(tmp_path / "xla-cache")
    full = _batch()
    state = _state(full)
    key = cache_key(model=CubeRegressor(), buckets=(8,))

    cold = build_aot_step(make_supervised_step(donate=False), state, full,
                          buckets=(8,), cache_dir=cache, key=key)
    assert cold.cache_misses == 2 and cold.cache_hits == 0

    warm = build_aot_step(make_supervised_step(donate=False), state, full,
                          buckets=(8,), cache_dir=cache, key=key)
    assert warm.cache_hits == 2 and warm.cache_misses == 0
    c = _counters()
    assert c.get("train.aot_cache_hits") == 2
    assert c.get("train.aot_cache_misses") == 2
    assert os.path.exists(os.path.join(cache, "aot_manifest.json"))


@pytest.mark.usefixtures("_cache_config_guard")
def test_manifest_key_isolation(tmp_path):
    """A different cache key (different model/ladder/mesh) never reads
    another key's manifest entries as warm."""
    cache = str(tmp_path / "xla-cache")
    full = _batch()
    state = _state(full)
    build_aot_step(make_supervised_step(donate=False), state, full,
                   buckets=(8,), cache_dir=cache, key="key-a")
    other = build_aot_step(make_supervised_step(donate=False), state, full,
                           buckets=(8,), cache_dir=cache, key="key-b")
    assert other.cache_misses == 2 and other.cache_hits == 0


def test_cache_key_anatomy():
    base = cache_key(model=CubeRegressor(), buckets=(1, 2, 4, 8))
    assert base == cache_key(model=CubeRegressor(), buckets=(1, 2, 4, 8))
    assert base != cache_key(model=CubeRegressor(), buckets=(8,))
    assert base != cache_key(model="other.Model", buckets=(1, 2, 4, 8))
    assert base != cache_key(model=CubeRegressor(), buckets=(1, 2, 4, 8),
                             precision="bf16")


# -- TrainDriver.build integration --------------------------------------------


def test_train_driver_build_stamps_startup_clocks():
    full = _batch()
    drv = TrainDriver.build(
        CubeRegressor(), full, optimizer=optax.sgd(0.01),
        inflight=2, sync_every=0, buckets=(8,),
    )
    assert isinstance(drv.step, AotStepSet)
    assert drv.startup_ms is not None and drv.startup_ms > 0
    assert drv.time_to_first_step_ms is None  # nothing retired yet
    for _ in range(3):
        drv.submit(dict(full))
    _, final = drv.finish()
    assert np.isfinite(final)
    stats = drv.stats
    assert stats["startup_ms"] == drv.startup_ms
    assert stats["time_to_first_step_ms"] is not None
    assert stats["time_to_first_step_ms"] >= 0
    assert _counters().get("train.aot_fallbacks") is None


def test_train_driver_build_requires_batch_dict():
    with pytest.raises(TypeError):
        TrainDriver.build(CubeRegressor(), np.zeros((8, HW, HW, 4), np.uint8))


def test_train_driver_build_resume_restores_state_and_counters(tmp_path):
    from blendjax.checkpoint import SnapshotManager

    full = _batch()
    with SnapshotManager(str(tmp_path), keep=2) as mgr:
        drv = TrainDriver.build(
            CubeRegressor(), full, optimizer=optax.sgd(0.01),
            inflight=2, sync_every=0, buckets=(8,),
        )
        for _ in range(4):
            drv.submit(dict(full))
        state, _ = drv.finish()
        mgr.save(4, state, session={"driver": drv.state_dict()})

    with SnapshotManager(str(tmp_path), keep=2) as mgr:
        resumed = TrainDriver.build(
            CubeRegressor(), full, optimizer=optax.sgd(0.01),
            inflight=2, sync_every=0, buckets=(8,),
            checkpoint=mgr, resume=True,
        )
        assert int(resumed.state.step) == 4
        assert resumed.resumed_session is not None
        assert resumed.startup_ms is not None
        # resumed driver keeps stepping through the warmed AOT set
        resumed.submit(dict(full))
        state, _ = resumed.finish()
        assert int(state.step) == 5
