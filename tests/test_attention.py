"""Local-attention backend dispatch (blendjax.ops.attention).

The flash kernel itself is TPU hardware (`-m tpu` tier); the dispatch
contract — explicit-request failures, auto fallback, the memory-driven
auto policy — is hermetic.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blendjax.ops.attention import (  # noqa: E402
    FLASH_RESIDUAL_BYTES,
    auto_picks_flash,
    flash_supported,
    local_attention,
    scores_residual_bytes,
)
from blendjax.parallel.ring import reference_attention  # noqa: E402


def _qkv(t=128, b=2, h=2, d=8, dtype=jnp.float32):
    k = jax.random.key(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(k, i), (b, t, h, d), dtype)
        for i in range(3)
    )


def test_flash_unsupported_off_tpu():
    q, _, _ = _qkv()
    if jax.default_backend() != "tpu":
        assert not flash_supported(q)
        assert not auto_picks_flash(q)


def test_explicit_flash_raises_when_unsupported():
    """Same contract as the tile decode's use_pallas: an explicit
    backend request must fail loudly, never silently measure xla."""
    if jax.default_backend() == "tpu":
        pytest.skip("flash is supported here")
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="flash attention backend"):
        local_attention(q, k, v, backend="flash")


def test_unknown_backend_rejected():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="unknown attention backend"):
        local_attention(q, k, v, backend="turbo")


def test_flash_support_checks_kv_length_too():
    """Cross-attention with an un-tileable KV length must not dispatch
    to the kernel (auto falls back; explicit flash raises)."""
    q, _, _ = _qkv(t=128)
    k_bad, _, _ = _qkv(t=120)
    assert not flash_supported(q, k_bad)


def test_flash_block_sizes_pinned_and_consistent():
    """Every flash call passes an EXPLICIT BlockSizes built from
    FLASH_BLOCK (kernel defaults drifting across jax upgrades change
    nothing): all block edges are pinned, none exceeds FLASH_BLOCK,
    and any shape flash_supported admits tiles the pinned grid
    exactly — eligibility and launch share one source of truth."""
    from blendjax.ops.attention import FLASH_BLOCK, flash_block_sizes

    for t_q, t_kv in [(128, 128), (3072, 3072), (256, 1024), (64, 128)]:
        bs = flash_block_sizes(t_q, t_kv)
        edges = {
            name: getattr(bs, name)
            for name in (
                "block_q", "block_k_major", "block_k", "block_b",
                "block_q_major_dkv", "block_k_major_dkv", "block_k_dkv",
                "block_q_dkv", "block_k_major_dq", "block_k_dq",
                "block_q_dq",
            )
        }
        assert all(v is not None for v in edges.values()), edges
        assert all(v <= FLASH_BLOCK for v in edges.values()), edges
        if t_q % FLASH_BLOCK == 0 and t_kv % FLASH_BLOCK == 0:
            # the admitted regime: every q-edge tiles t_q, every
            # k-edge tiles t_kv — the grid flash_supported promised
            for name, v in edges.items():
                if name == "block_b":
                    continue
                t = t_q if name.startswith("block_q") else t_kv
                assert t % v == 0, (name, v, t_q, t_kv)


def test_scores_residual_bytes_and_auto_threshold():
    """The auto policy is memory-driven: f32 prob-residual bytes per
    call against FLASH_RESIDUAL_BYTES (in-model, the materialized path
    measured FASTER than the kernel at every length HBM absorbs —
    docs in the module header — so flash engages only where xla
    becomes infeasible)."""
    class Q:
        ndim = 4

        def __init__(self, b, t, h, d):
            self.shape = (b, t, h, d)

    # f32 probs saved for backward (measured ~600 MB at this shape)
    assert scores_residual_bytes(Q(4, 3072, 4, 128)) == 4 * 4 * 3072**2 * 4
    # ~604 MB at the bench longseq shape: under the 2 GiB bar
    assert scores_residual_bytes(Q(4, 3072, 4, 128)) < FLASH_RESIDUAL_BYTES
    # T=16k at B=1, H=4 (the module docstring's OOM example): ~4.3 GB
    assert scores_residual_bytes(Q(1, 16384, 4, 128)) > FLASH_RESIDUAL_BYTES


@pytest.mark.parametrize("backend", ["auto", "xla"])
def test_dispatch_matches_reference_off_tpu(backend):
    """Off-TPU, every backend choice resolves to the xla path."""
    q, k, v = _qkv()
    out = local_attention(q, k, v, backend=backend)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)),
        atol=1e-6,
    )


@pytest.mark.tpu
def test_flash_matches_reference_on_tpu():
    """Kernel parity on real hardware
    (run with BLENDJAX_TEST_TPU=1 pytest -m tpu)."""
    # self-skip beats relying on the marker filter: a pytest invocation
    # overriding -m (e.g. `-m 'not slow'`) runs this on the CPU mesh,
    # where the kernel is structurally unsupported
    import jax

    if jax.default_backend() != "tpu":
        pytest.skip("flash kernel needs a real TPU")
    q, k, v = _qkv(t=1024, h=4, d=128, dtype=jnp.bfloat16)
    assert flash_supported(q)
    for causal in (False, True):
        out = local_attention(q, k, v, causal=causal, backend="flash")
        ref = reference_attention(q, k, v, causal=causal)
        diff = float(
            jnp.max(jnp.abs(out.astype(jnp.float32)
                            - ref.astype(jnp.float32)))
        )
        # bar is a few bf16 ulps at the output magnitudes (~2-4 on the
        # causal path's early rows, where one ulp is 2^-6)
        assert diff < 2e-2, (causal, diff)
    # auto at this (small-residual) shape takes the xla path — the
    # memory-driven policy — and still matches
    out_auto = local_attention(q, k, v, backend="auto")
    np.testing.assert_allclose(
        np.asarray(out_auto.astype(jnp.float32)),
        np.asarray(reference_attention(q, k, v).astype(jnp.float32)),
        atol=2e-2,
    )
