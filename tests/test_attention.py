"""Local-attention backend dispatch (blendjax.ops.attention).

The flash kernel itself is TPU hardware (`-m tpu` tier); the dispatch
contract — explicit-request failures, auto fallback, crossover policy —
is hermetic.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blendjax.ops.attention import (  # noqa: E402
    FLASH_MIN_TOKENS,
    flash_supported,
    local_attention,
)
from blendjax.parallel.ring import reference_attention  # noqa: E402


def _qkv(t=128, b=2, h=2, d=8, dtype=jnp.float32):
    k = jax.random.key(0)
    return tuple(
        jax.random.normal(jax.random.fold_in(k, i), (b, t, h, d), dtype)
        for i in range(3)
    )


def test_flash_unsupported_off_tpu():
    q, _, _ = _qkv()
    if jax.default_backend() != "tpu":
        assert not flash_supported(q)


def test_explicit_flash_raises_when_unsupported():
    """Same contract as the tile decode's use_pallas: an explicit
    backend request must fail loudly, never silently measure xla."""
    if jax.default_backend() == "tpu":
        pytest.skip("flash is supported here")
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="flash attention backend"):
        local_attention(q, k, v, backend="flash")


def test_unknown_backend_rejected():
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="unknown attention backend"):
        local_attention(q, k, v, backend="turbo")


def test_flash_support_checks_kv_length_too():
    """Cross-attention with an un-tileable KV length must not dispatch
    to the kernel (auto falls back; explicit flash raises)."""
    from blendjax.ops.attention import flash_supported

    q, _, _ = _qkv(t=128)
    k_bad, _, _ = _qkv(t=120)
    assert not flash_supported(q, k_bad)


@pytest.mark.parametrize("backend", ["auto", "xla"])
def test_dispatch_matches_reference_off_tpu(backend):
    """Off-TPU, every backend choice resolves to the xla path."""
    q, k, v = _qkv()
    out = local_attention(q, k, v, backend=backend)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)),
        atol=1e-6,
    )


@pytest.mark.tpu
def test_flash_matches_reference_on_tpu():
    """Kernel parity on real hardware, above the auto crossover
    (run with BLENDJAX_TEST_TPU=1 pytest -m tpu)."""
    t = max(FLASH_MIN_TOKENS, 1024)
    q, k, v = _qkv(t=t, h=4, d=128, dtype=jnp.bfloat16)
    assert flash_supported(q)
    for causal in (False, True):
        out = local_attention(q, k, v, causal=causal, backend="flash")
        ref = reference_attention(q, k, v, causal=causal)
        diff = float(
            jnp.max(jnp.abs(out.astype(jnp.float32)
                            - ref.astype(jnp.float32)))
        )
        # bar is a few bf16 ulps at the output magnitudes (~2-4 on the
        # causal path's early rows, where one ulp is 2^-6)
        assert diff < 2e-2, (causal, diff)
    # and auto picks flash at this length without changing results
    out_auto = local_attention(q, k, v, backend="auto")
    np.testing.assert_allclose(
        np.asarray(out_auto.astype(jnp.float32)),
        np.asarray(local_attention(q, k, v, backend="flash")
                   .astype(jnp.float32)),
    )
