"""Auxiliary subsystems: metrics, failure detection, respawn recovery."""

import time

import numpy as np
import pytest

from blendjax.transport import DataPublisherSocket, ReceiveTimeoutError
from blendjax.utils.metrics import Metrics, metrics


def test_metrics_counters_gauges_spans():
    m = Metrics()
    m.count("x")
    m.count("x", 2)
    m.gauge("depth", 7)
    with m.span("work"):
        time.sleep(0.01)
    rep = m.report()
    assert rep["counters"]["x"] == 3
    assert rep["gauges"]["depth"] == 7
    assert rep["spans"]["work"]["count"] == 1
    assert rep["spans"]["work"]["mean_ms"] >= 5
    # spans feed same-name histograms: count parity is structural
    assert rep["histograms"]["work"]["count"] == 1
    assert rep["spans"]["work"]["p95_ms"] >= 5
    m.reset()
    assert m.report() == {
        "counters": {}, "gauges": {}, "spans": {}, "histograms": {},
    }


def test_ingest_populates_default_metrics():
    import threading

    from blendjax.data import HostIngest, RemoteStream

    metrics.reset()
    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ingest = HostIngest(
        RemoteStream([pub.addr], timeoutms=5000, max_items=4), batch_size=2
    )
    t = threading.Thread(
        target=lambda: [
            pub.publish(image=np.zeros((4, 4), np.uint8), frameid=i)
            for i in range(4)
        ],
        daemon=True,
    )
    t.start()
    assert len(list(ingest)) == 2
    t.join(timeout=5)
    rep = metrics.report()
    assert rep["counters"]["ingest.items"] == 4
    assert rep["counters"]["ingest.batches"] == 2
    pub.close()


def test_stream_on_timeout_retry_then_fail():
    from blendjax.data import RemoteStream

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    calls = []

    def on_timeout():
        calls.append(1)
        return len(calls) < 3

    stream = RemoteStream([pub.addr], timeoutms=50, on_timeout=on_timeout)
    with pytest.raises(ReceiveTimeoutError):
        next(iter(stream))
    assert len(calls) == 3
    pub.close()


def test_pipeline_timeout_reports_dead_producer():
    """With a launcher attached, a feed stall names the dead instance
    instead of raising an opaque timeout."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script="-c",
        script_args=["import sys; sys.exit(7)"],
        num_instances=1,
    ) as launcher:
        launcher.processes[0].wait(timeout=30)
        addr = "tcp://127.0.0.1:49999"  # nothing listens; timeout path
        with StreamDataPipeline(
            [addr], batch_size=2, launcher=launcher, timeoutms=100
        ) as pipe:
            with pytest.raises(RuntimeError, match="died.*7"):
                next(iter(pipe))


def test_pipeline_respawn_keeps_stream_alive():
    """respawn=True + launcher-integrated timeout: killing the producer
    mid-stream recovers without consumer-visible failure."""
    import os

    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    producer = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        respawn=True,
        instance_args=[["--shape", "32", "32"]],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4,
            launcher=launcher, timeoutms=3000,
        ) as pipe:
            it = iter(pipe)
            next(it)
            # kill the producer; respawn via the timeout path revives it
            launcher.processes[0].terminate()
            batch = next(it)
            assert batch["image"].shape == (4, 32, 32, 4)


def test_tile_stream_metrics_expose_compression_ratio():
    """The pipeline counts wire vs decoded bytes so the sparse-stream
    compression ratio is observable (SURVEY.md §5: instrument ingest)."""
    import os

    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.utils.metrics import metrics

    producer = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    before = dict(metrics.counters)
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4, timeoutms=30_000,
            max_items=3,
        ) as pipe:
            batches = list(pipe)
    assert len(batches) == 3
    wire = metrics.counters["tiles.wire_bytes"] - before.get(
        "tiles.wire_bytes", 0
    )
    decoded = metrics.counters["tiles.decoded_bytes"] - before.get(
        "tiles.decoded_bytes", 0
    )
    assert decoded == 3 * 4 * 64 * 64 * 4
    assert 0 < wire < decoded  # compressed on the wire
