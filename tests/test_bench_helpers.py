"""Guards for bench.py's measurement helpers (they feed BENCH_r*.json,
the judged record — a silent mis-measurement is worse than a crash)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_model_flops_is_the_ledger_probe():
    """bench.py re-exports the device ledger's probe (the one home for
    the cost-model path) — a second copy drifting in bench.py is how
    the MFU denominator silently forks."""
    import bench

    from blendjax.obs import devledger

    assert bench.measure_model_flops is devledger.measure_model_flops


def test_model_flops_matches_analytic_count():
    """cost_analysis-derived FLOPs/img must agree with the analytic
    conv count — catches the lax.scan-body-counted-once class of bug
    (r4 shipped a 16x undercount briefly) and any future model/shape
    drift that silently changes the MFU denominator."""
    import bench

    fl = bench.measure_model_flops()
    got = fl["flops_per_image"]

    # Analytic fwd FLOPs for CubeRegressor at 480x640: stride-2 3x3
    # convs (32, 64, 128, 256) + the dense head; backward ~2x forward.
    h, w, cin = 480, 640, 4
    fwd = 0
    for f in (32, 64, 128, 256):
        h, w = h // 2, w // 2
        fwd += 2 * 9 * cin * f * h * w
        cin = f
    fwd += 2 * 256 * 256 + 2 * 256 * 16  # dense head
    analytic = 3 * fwd  # fwd + ~2x bwd
    assert 0.7 * analytic < got < 1.3 * analytic, (got, analytic)


def test_ceiling_ratio_row_publication_rules():
    """utilization_vs_ceiling publishes a number ONLY when headline and
    ceiling share fit windows and the ratio is sane — the r4 record
    published 1.577 from a cross-window comparison (VERDICT r4 #1)."""
    import bench

    fitc = {"img_s": 600.0, "fit_window": True}
    assert bench.ceiling_ratio_row(570.0, fitc, True) == 0.95
    # live "beating" the ceiling beyond noise: windows weren't
    # equivalent after all — invalid, uncomparable number preserved
    r = bench.ceiling_ratio_row(700.0, fitc, True)
    assert r["invalid"] == "window_mismatch"
    assert r["uncomparable_ratio"] == 1.167
    # unfit headline / unfit ceiling / capped ceiling -> weather-invalid
    assert (
        bench.ceiling_ratio_row(570.0, fitc, False)["invalid"] == "weather"
    )
    assert bench.ceiling_ratio_row(
        570.0, {"img_s": 600.0, "fit_window": False}, True
    )["invalid"] == "weather"
    assert bench.ceiling_ratio_row(
        570.0, {"img_s": 600.0, "fit_window": True, "capped": True}, True
    )["invalid"] == "weather"
    assert bench.ceiling_ratio_row(570.0, {}, True)["invalid"] == (
        "ceiling_failed"
    )


def test_tile_capacity_default_derives_from_dims():
    """Measured geometries keep their measured fits; any other geometry
    gets an area-scaled estimate that covers the known changed-pixel
    budget (ADVICE r4: a 32x32 override silently got the 16x16 fit)."""
    import bench

    assert bench.tile_capacity_default(16, 16) == "288"
    assert bench.tile_capacity_default(16, 32) == "160"
    cap = int(bench.tile_capacity_default(32, 32))
    grid = 15 * 20  # 480/32 x 640/32
    assert 32 <= cap <= grid and cap % 32 == 0
    assert cap * 32 * 32 >= 282 * 256  # covers the measured budget
    # tiny grids (huge tiles) clamp to the grid, not up to 32
    assert int(bench.tile_capacity_default(240, 320)) == 4


def test_weather_probe_reports_window():
    """The per-pass weather stamp must always carry a fit verdict and,
    absent device errors, the RTT it judged from."""
    import bench

    w = bench.weather_probe()
    assert isinstance(w.get("fit"), bool)
    if "error" not in w:
        assert "rtt_s" in w


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.t += s


def _probe_seq(probes, clock, probe_cost=1.0):
    """Iterator-backed fake probe; repeats the last element forever and
    advances the fake clock per call (probes aren't free)."""
    it = iter(probes)
    last = probes[-1]

    def probe():
        nonlocal last
        clock.t += probe_cost
        last = next(it, last)
        return dict(last)

    return probe


FIT = {"fit": True, "rtt_s": 0.1, "h2d_MB_s": 43.0}
COLLAPSED = {"fit": False, "rtt_s": 0.1, "h2d_MB_s": 12.0}
BLIND = {"fit": False, "error": "boom"}


def _measure_seq(values, clock, cost=5.0):
    it = iter(values)
    last = values[-1]

    def run():
        nonlocal last
        clock.t += cost
        last = next(it, last)
        return {"value": last, "seconds": cost}

    return run


def test_collect_passes_stops_at_n_fit_passes_over_floor():
    import bench

    clock = _Clock()
    passes = bench.collect_passes(
        _measure_seq([500.0, 520.0], clock),
        _probe_seq([FIT], clock),
        n_passes=2, retry_floor=400.0, wait_budget=480.0, poll_sleep=12.0,
        degraded=False, w0=FIT, clock=clock, sleep=clock.sleep,
    )
    assert [p["value"] for p in passes] == [500.0, 520.0]
    assert all(p["fit_window"] for p in passes)
    # stopped as soon as the goal was met — no budget-burning extras
    assert clock.t < 60


def test_collect_passes_keeps_rolling_below_floor():
    """Fit-probe windows whose passes run slow (the 38 MB/s + stalled
    dispatch mode) must not satisfy the bench — it keeps rolling until
    the budget or the 20-pass cap."""
    import bench

    clock = _Clock()
    passes = bench.collect_passes(
        _measure_seq([60.0], clock),
        _probe_seq([FIT], clock),
        n_passes=2, retry_floor=400.0, wait_budget=200.0, poll_sleep=12.0,
        degraded=False, w0=FIT, clock=clock, sleep=clock.sleep,
    )
    assert len(passes) >= 3  # kept retrying
    assert clock.t >= 200.0 or len(passes) == 20


def test_collect_passes_fallback_when_never_fit():
    """No fit window in the whole budget -> measure anyway, labeled."""
    import bench

    clock = _Clock()
    passes = bench.collect_passes(
        _measure_seq([20.0], clock),
        _probe_seq([COLLAPSED], clock),
        n_passes=3, retry_floor=400.0, wait_budget=60.0, poll_sleep=12.0,
        degraded=False, w0=COLLAPSED, clock=clock, sleep=clock.sleep,
    )
    assert len(passes) == 3
    assert not any(p["fit_window"] for p in passes)


def test_collect_passes_blind_probe_escape():
    """Probes with no bandwidth figure can never turn fit — escape to
    the fallback after 3 instead of sleeping the budget away."""
    import bench

    clock = _Clock()
    passes = bench.collect_passes(
        _measure_seq([20.0], clock),
        _probe_seq([BLIND], clock),
        n_passes=2, retry_floor=400.0, wait_budget=480.0, poll_sleep=12.0,
        degraded=False, w0=BLIND, clock=clock, sleep=clock.sleep,
    )
    assert len(passes) == 2
    # 3 blind polls (2 sleeps between) + fallback probes; far under budget
    assert clock.t < 100


def test_collect_passes_degraded_skips_probes():
    """Outage mode: zero probe calls (each costs multi-second RTTs);
    w0 stamps the first pass, the skip marker the rest."""
    import bench

    clock = _Clock()
    calls = {"probes": 0}

    def probe():
        calls["probes"] += 1
        return dict(BLIND)

    w0 = {"fit": False, "rtt_s": 24.0}
    passes = bench.collect_passes(
        _measure_seq([5.0], clock), probe,
        n_passes=2, retry_floor=400.0, wait_budget=0.0, poll_sleep=12.0,
        degraded=True, w0=w0, clock=clock, sleep=clock.sleep,
    )
    assert calls["probes"] == 0
    assert len(passes) == 2
    assert passes[0]["weather"]["pre"] == w0
    assert passes[1]["weather"]["pre"].get("skipped") == "outage"


def test_collect_passes_fallback_is_probe_free():
    """ADVICE r5: once the wait budget is spent, fallback passes must
    not issue fresh probe() calls (on a degraded link each costs
    multi-second RTTs that eat the watchdog budget) — the first
    fallback pass reuses the LAST poll probe, the rest carry the skip
    marker, and no pass gets a post probe."""
    import bench

    clock = _Clock()
    seq = []

    def probe():
        seq.append("probe")
        clock.t += 2.0
        return dict(COLLAPSED)

    inner = _measure_seq([20.0], clock)

    def run():
        seq.append("measure")
        return inner()

    passes = bench.collect_passes(
        run, probe,
        n_passes=3, retry_floor=400.0, wait_budget=30.0, poll_sleep=12.0,
        degraded=False, w0=COLLAPSED, clock=clock, sleep=clock.sleep,
    )
    assert len(passes) == 3
    # the poll loop probed; the fallback (everything from the first
    # measure onward) issued ZERO fresh probes
    assert seq.index("measure") > 0
    assert "probe" not in seq[seq.index("measure"):]
    assert passes[0]["weather"]["pre"] == COLLAPSED  # last poll reused
    for p in passes:
        assert p["weather"]["post"].get("skipped") == "outage"
    for p in passes[1:]:
        assert p["weather"]["pre"].get("skipped") == "outage"


def test_collect_passes_zero_budget_first_pass_stamped_by_w0():
    """wait_budget=0 (the CI smoke config): no poll probe ever ran, so
    the run-start probe stamps the first fallback pass and still no
    fresh probes are issued."""
    import bench

    clock = _Clock()
    calls = {"probes": 0}

    def probe():
        calls["probes"] += 1
        return dict(FIT)

    passes = bench.collect_passes(
        _measure_seq([20.0], clock), probe,
        n_passes=2, retry_floor=400.0, wait_budget=0.0, poll_sleep=12.0,
        degraded=False, w0=COLLAPSED, clock=clock, sleep=clock.sleep,
    )
    assert calls["probes"] == 0
    assert len(passes) == 2
    assert passes[0]["weather"]["pre"] == COLLAPSED
    assert passes[1]["weather"]["pre"].get("skipped") == "outage"


def test_collect_passes_flap_mid_pass_is_not_fit():
    """pre fit, post collapsed -> the window didn't hold; the pass is
    recorded but not fit (the r4 lesson: pre-only gating was defeated
    by mid-run flaps)."""
    import bench

    clock = _Clock()
    passes = bench.collect_passes(
        _measure_seq([300.0], clock),
        _probe_seq([FIT, COLLAPSED], clock),  # pre fit, post collapsed
        n_passes=1, retry_floor=150.0, wait_budget=30.0, poll_sleep=12.0,
        degraded=False, w0=FIT, clock=clock, sleep=clock.sleep,
    )
    assert passes[0]["fit_window"] is False


def _row_fn(values, clock, cost=5.0):
    it = iter(values)
    last = values[-1]

    def fn():
        nonlocal last
        clock.t += cost
        last = next(it, last)
        return {"img_s": last}

    return fn


def test_gated_row_polls_for_fit_when_headline_fit():
    import bench

    clock = _Clock()
    row = bench.run_gated_row(
        _row_fn([600.0], clock),
        _probe_seq([COLLAPSED, COLLAPSED, FIT, FIT], clock),
        headline_fit=True, degraded=False, budget=180.0,
        poll_sleep=12.0, clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is True
    assert row["weather"]["pre"]["h2d_MB_s"] == 43.0


def test_gated_row_runs_immediately_when_headline_unfit():
    import bench

    clock = _Clock()
    probes = {"n": 0}

    def probe():
        probes["n"] += 1
        clock.t += 1.0
        return dict(COLLAPSED)

    row = bench.run_gated_row(
        _row_fn([100.0], clock), probe,
        headline_fit=False, degraded=False, budget=180.0,
        poll_sleep=12.0, clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is False
    assert probes["n"] == 2  # pre + post only: no polling, no retry
    assert clock.t < 10


def test_gated_row_retries_once_after_midrow_collapse():
    import bench

    clock = _Clock()
    # attempt 1: pre fit, post collapsed AND BOTH decayed re-probes
    # still collapsed (a real mid-row flap); attempt 2: fit holds
    row = bench.run_gated_row(
        _row_fn([500.0, 510.0], clock),
        _probe_seq(
            [FIT, COLLAPSED, COLLAPSED, COLLAPSED, FIT, FIT], clock
        ),
        headline_fit=True, degraded=False, budget=180.0,
        poll_sleep=12.0, clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is True
    assert row["img_s"] == 510.0  # the retry's measurement


def test_gated_row_single_jitter_sample_cannot_invalidate():
    """One collapsed post sample between two fit ones is host jitter,
    not weather: the immediate re-probe absorbs it, the row stays fit
    on its FIRST measurement, and the discarded sample is preserved
    (the BENCH_r05 `utilization.invalid: "weather"` mode)."""
    import bench

    clock = _Clock()
    row = bench.run_gated_row(
        _row_fn([500.0, 510.0], clock),
        _probe_seq([FIT, COLLAPSED, FIT], clock),
        headline_fit=True, degraded=False, budget=180.0,
        poll_sleep=12.0, clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is True
    assert row["img_s"] == 500.0  # no re-measurement needed
    assert row["weather"]["post"]["jitter_discarded"] == 12.0


def test_gated_row_decaying_bar_accepts_jittered_reprobe():
    """A post sample under the full fit bar but above the decayed
    re-probe bar (teardown jitter, not a collapse) keeps the window
    fit: re-probe 1 judges at 0.9x the bar, re-probe 2 at 0.81x — the
    BENCH_r05 mode where one re-probe at the full bar still
    invalidated `utilization` with `invalid: "weather"`."""
    import bench

    clock = _Clock()
    near_fit = {"fit": False, "rtt_s": 0.1, "h2d_MB_s": 33.0}
    # 33.0 fails the 35.0 bar and the first decayed bar (31.5 passes!)
    # -> accepted on re-probe 1 with the relaxed-bar stamp
    row = bench.run_gated_row(
        _row_fn([500.0], clock),
        _probe_seq([FIT, near_fit, near_fit], clock),
        headline_fit=True, degraded=False, budget=180.0,
        poll_sleep=12.0, clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is True
    assert row["img_s"] == 500.0  # no re-measurement needed
    post = row["weather"]["post"]
    assert post["relaxed_bar_MB_s"] == 31.5  # 35.0 * 0.9
    assert post["jitter_discarded"] == 33.0
    # a genuinely collapsed window fails every decayed bar and the
    # discarded samples are all preserved
    clock2 = _Clock()
    row2 = bench.run_gated_row(
        _row_fn([500.0], clock2),
        _probe_seq([FIT, COLLAPSED], clock2),
        headline_fit=True, degraded=False, budget=10.0, attempts=1,
        poll_sleep=12.0, clock=clock2, sleep=clock2.sleep,
    )
    assert row2["fit_window"] is False
    assert "jitter_discarded" not in row2["weather"]["post"]


def test_utilization_row_partial_instead_of_invalid():
    """Cross-window utilization publishes a one-sided lower bound with
    an explicit `partial` flag — never the old `invalid: "weather"`
    wholesale discard (the recurring r05 outcome)."""
    import bench

    fit_alone = {"img_s": 1000.0, "fit_window": True}
    assert bench.utilization_row(500.0, fit_alone, True) == 0.5
    p = bench.utilization_row(500.0, fit_alone, False)
    assert p["partial"] is True and p["one_sided"] == 0.5
    assert p["reason"] == "weather"
    assert p["headline_fit"] is False and p["step_alone_fit"] is True
    # unfit headline deflates the numerator: the figure is a floor
    assert p["bound"] == "lower"
    p2 = bench.utilization_row(
        500.0, {"img_s": 1000.0, "fit_window": False}, True
    )
    assert p2["partial"] is True and p2["step_alone_fit"] is False
    # unfit step-alone deflates the DENOMINATOR: the figure can only
    # overstate utilization — it must publish as an upper bound
    assert p2["bound"] == "upper"
    p3 = bench.utilization_row(
        500.0, {"img_s": 1000.0, "fit_window": False}, False
    )
    assert p3["bound"] == "unknown"
    assert all("invalid" not in x for x in (p, p2, p3))
    assert bench.utilization_row(500.0, {}, True)["invalid"] == (
        "step_alone_failed"
    )


def test_gated_row_degraded_skips_probes_entirely():
    import bench

    clock = _Clock()

    def probe():  # pragma: no cover - must not be called
        raise AssertionError("probe called in degraded mode")

    row = bench.run_gated_row(
        _row_fn([5.0], clock), probe,
        headline_fit=False, degraded=True,
        clock=clock, sleep=clock.sleep,
    )
    assert row["fit_window"] is False
    assert row["weather"]["pre"].get("skipped") == "outage"


def test_pipelined_ceiling_caps_and_flags(monkeypatch):
    """A ceiling run that exceeds its time cap must return what it
    measured, flagged 'capped' (a silently depressed ceiling would
    publish utilization_vs_ceiling > 1 as if live beat the runtime).

    Bench-shape constants are shrunk for the CPU mesh (the cap logic is
    shape-independent; full 640x480 CPU convs would cost ~6 min)."""
    import bench

    monkeypatch.setattr(bench, "SHAPE", (64, 64))
    monkeypatch.setattr(bench, "BATCH", 8)
    out = bench.measure_pipelined_ceiling(2, items=32, time_cap=0.0)
    assert out["images"] > 0 and out["img_s"] > 0
    assert out.get("capped") is True


def test_live_overlap_row_shape(monkeypatch):
    """The async-overlap A/B row runs both legs for real through the
    fused driver path and reports the record's contract: zero
    standalone decode dispatches, exactly one jit call per driver step
    (the bench-smoke CI assertion), driver ring stats, and the
    throughput ratio. Bench shapes shrunk for the CPU mesh like the
    rows above."""
    import bench

    monkeypatch.setattr(bench, "SHAPE", (64, 64))
    monkeypatch.setattr(bench, "_TILE_ARGS", ["16"])
    monkeypatch.setattr(bench, "TILE_CAPACITY", "16")
    monkeypatch.setenv("BLENDJAX_BENCH_INSTANCES", "2")
    row = bench.measure_live_overlap(
        chunk=2, items=16, time_cap=10.0, inflight=3
    )
    assert row["inflight1"]["img_s"] > 0
    assert row["inflight3"]["img_s"] > 0
    assert row["decode_dispatch_eliminated"] is True
    assert row["dispatch_per_step"] == 1.0
    for leg in ("inflight1", "inflight3"):
        assert row[leg]["decode_dispatch_count"] == 0
        assert row[leg]["train_dispatch_count"] == row[leg]["dispatches"]
        assert row[leg]["steps_in_flight_hwm"] <= 3
    assert row["value"] == pytest.approx(
        row["inflight3"]["img_s"] / row["inflight1"]["img_s"], rel=1e-3
    )


def test_live_echo_row_shape(monkeypatch):
    """The data-echoing A/B row runs the off and echo legs for real
    through pipeline + reservoir + TrainDriver and reports the record's
    contracts: exact echo accounting (fresh + echoed == steps * batch),
    exactly one DEVICE dispatch per driver step under FULL accounting
    (train + standalone reservoir gathers + decodes — the echo leg runs
    the fused draw, so standalone gathers are zero), the donation-reuse
    audit, unique fraction, and the step-rate ratio. Bench shapes
    shrunk for the CPU mesh like the rows above."""
    import bench

    monkeypatch.setattr(bench, "SHAPE", (64, 64))
    monkeypatch.setattr(bench, "_TILE_ARGS", ["16"])
    monkeypatch.setattr(bench, "TILE_CAPACITY", "16")
    row = bench.measure_live_echo(
        items=16, time_cap=10.0, factors=(4,), capacity=64
    )
    assert row["off"]["step_img_s"] > 0
    assert row["echo4"]["step_img_s"] > 0
    assert row["accounting_exact"] is True
    assert row["dispatch_per_step"] == 1.0
    leg = row["echo4"]
    assert leg["max_echo_factor"] == 4
    assert leg["fused_draw"] is True
    # the full dispatch accounting's teeth: zero standalone reservoir
    # gathers at the step cadence (pre-fusion this was one per step)
    assert leg["echo_sample_dispatches"] == 0
    # the runtime donation audit held on every leg: ring + state
    # buffers updated in place, never copied
    assert row["donation_reuse"] is True
    assert leg["donation_audit"]["reservoir"]["stable"] is True
    assert leg["donation_audit"]["state"]["stable"] is True
    assert 0.0 < leg["unique_fraction"] <= 1.0
    assert leg["echo_counters"]["echo.fresh"] + leg["echo_counters"][
        "echo.echoed"
    ] == leg["steps"] * bench.BATCH
    assert row["off"]["unique_fraction"] == 1.0
    assert row["value"] == pytest.approx(
        row["echo4"]["step_img_s"] / row["off"]["step_img_s"], abs=5e-4
    )


def test_precision_ab_row_shape():
    """The precision A/B row reports BOTH policies with step-alone
    img/s and an mfu_step_alone key on the CNN and longseq legs (None
    off-v5e — the key's presence is the CI structural assertion), plus
    the throughput ratios."""
    import bench

    row = bench.measure_precision_ab()
    assert set(row["legs"]) == {"bf16-compute", "bf16-grads"}
    for leg in row["legs"].values():
        for sub in ("cnn", "longseq"):
            assert leg[sub]["img_s"] > 0
            assert "mfu_step_alone" in leg[sub]
        assert leg["longseq"]["tokens"] > 0
    assert row["value"] > 0
    assert row["longseq_ratio"] > 0
    assert row["full_geometry"] is False  # CPU suite runs shrunk shapes


def test_ingest_workers_ab_row_shape(monkeypatch):
    """The sharded-ingest A/B row runs both legs for real and reports
    the contract the record promises: per-shard ingest.recv spans on
    the workers-2 leg, the wire byte pair on both, and the throughput
    ratio. Bench-shape constants shrunk for the CPU mesh like the
    ceiling test above."""
    import bench

    monkeypatch.setattr(bench, "SHAPE", (64, 64))
    monkeypatch.setattr(bench, "_TILE_ARGS", ["16"])
    monkeypatch.setattr(bench, "TILE_CAPACITY", "16")
    monkeypatch.setenv("BLENDJAX_BENCH_INSTANCES", "2")
    row = bench.measure_ingest_workers_ab(chunk=2, items=16, time_cap=10.0)
    assert row["workers1"]["img_s"] > 0 and row["workers2"]["img_s"] > 0
    assert row["value"] == pytest.approx(
        row["workers2"]["img_s"] / row["workers1"]["img_s"], rel=1e-3
    )
    assert "ingest.recv" in row["workers1"]["recv_spans"]
    shard_spans = set(row["workers2"]["recv_spans"])
    assert {"ingest.recv.shard0", "ingest.recv.shard1"} <= shard_spans
    for leg in ("workers1", "workers2"):
        wire = row[leg]["wire"]
        assert wire["wire.raw_bytes"] >= wire["wire.compressed_bytes"] > 0


def test_multichip_live_legs_shape(monkeypatch):
    """The multichip_live row runs the REAL live mesh path (synthetic
    producers -> sharded ingest -> mesh feeder -> MeshTrainDriver) per
    mesh size and reports the record's contracts: one dispatch per
    step at every size, zero decode dispatches, zero wire gaps, and
    the weak-scaling speedup/efficiency pair. Shrunk to two mesh sizes
    and short windows for the CPU suite; the structure is identical to
    the full 1/2/4/8 row."""
    import bench

    monkeypatch.setattr(bench, "MULTICHIP_PASSES", 1)
    row = bench._multichip_live_legs(mesh_sizes=(1, 4), time_cap=1.5)
    assert set(row["legs"]) == {"1", "4"}
    for n, leg in row["legs"].items():
        assert leg["img_s"] > 0
        assert leg["global_batch"] == row["b_dev"] * int(n)
        assert leg["dispatch_per_step"] == 1.0
        assert leg["decode_dispatch_count"] == 0
    assert row["seq_gaps"] == 0
    assert row["contracts_held_every_pass"] is True
    assert row["dispatch_per_step"] == 1.0
    assert row["decode_dispatch_eliminated"] is True
    assert row["speedup"] == pytest.approx(
        row["legs"]["4"]["img_s"] / row["legs"]["1"]["img_s"], rel=1e-3
    )
    assert row["scaling_efficiency"] == pytest.approx(
        row["speedup"] / 4, rel=1e-2
    )


def test_live_resume_row_shape(tmp_path, monkeypatch):
    """The kill-9/resume row runs its three child processes for real
    (uninterrupted reference, paced-then-SIGKILLed, resumed) and
    reports the record's contracts: identical f32 trajectories, zero
    wire gaps with the restart detected through the restored lineage,
    one dispatch per step with checkpointing enabled, and >= 1
    committed async save. Shrunk step count for the CPU suite."""
    import bench

    monkeypatch.setattr(bench, "RESUME_DIR", str(tmp_path / "snaps"))
    row = bench.measure_live_resume(steps=8)
    assert row["equality"]["identical"] is True
    assert row["equality"]["max_abs_diff"] == 0.0
    assert row["killed_mid_run"] is True
    assert row["committed_before_kill"] is True
    assert row["resumed_at"] >= 1
    assert row["seq_gaps"] == 0
    assert row["restart_detected"] is True
    assert row["dispatch_per_step"] == 1.0
    assert row["ckpt"]["saves"] >= 1
    assert row["value"] == 1.0


def test_wire_equality_contract():
    """live_wire_ab's equality leg, standalone: the SAME recorded wire
    bytes decoded as deferred "ndr" (run-length expansion inside the
    fused train dispatch) vs host-inflated "nd" fields train to
    IDENTICAL f32 loss — the device decompression changes where the
    bytes expand, never what the step computes."""
    import bench

    row = bench.measure_wire_equality(steps=6)
    assert row["identical"] is True
    assert row["max_abs_diff"] == 0.0
    assert row["ndr_loss"] == row["nd_loss"]


@pytest.mark.slow
def test_live_wire_ab_row_shape(monkeypatch):
    """The full wire-decode A/B row against real rate-capped synthetic
    producers: both legs report wire bytes + host decode-cost p95 +
    settled rates, the ndr leg holds the one-dispatch contract with
    ZERO standalone decode dispatches, no wire gaps, and the
    live-to-step-alone ratio is computed against the SAME fused step."""
    import bench

    row = bench.measure_live_wire_ab(time_cap=6.0)
    for name in ("ndz", "ndr"):
        leg = row[name]
        assert leg["steps"] > 0, (name, leg)
        assert leg["wire_bytes"] > 0, (name, leg)
        assert "decode_ms_p95" in leg and "settled_img_s" in leg
    assert row["ndr"]["dispatch_per_step"] == 1.0, row["ndr"]
    assert row["ndr"]["decode_dispatch_count"] == 0, row["ndr"]
    assert row["ndr"]["decode_ms_p95"] == 0.0, row["ndr"]
    assert row["ndr"]["rle_counters"].get("rle.batches", 0) > 0
    assert row["seq_gaps"] == 0, row
    assert row["equality"]["identical"] is True, row["equality"]
    assert row["step_alone"]["img_s"] > 0
    assert row["value"] == row["live_to_alone"] > 0
