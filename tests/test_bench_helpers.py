"""Guards for bench.py's measurement helpers (they feed BENCH_r*.json,
the judged record — a silent mis-measurement is worse than a crash)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")


def test_model_flops_matches_analytic_count():
    """cost_analysis-derived FLOPs/img must agree with the analytic
    conv count — catches the lax.scan-body-counted-once class of bug
    (r4 shipped a 16x undercount briefly) and any future model/shape
    drift that silently changes the MFU denominator."""
    import bench

    fl = bench.measure_model_flops()
    got = fl["flops_per_image"]

    # Analytic fwd FLOPs for CubeRegressor at 480x640: stride-2 3x3
    # convs (32, 64, 128, 256) + the dense head; backward ~2x forward.
    h, w, cin = 480, 640, 4
    fwd = 0
    for f in (32, 64, 128, 256):
        h, w = h // 2, w // 2
        fwd += 2 * 9 * cin * f * h * w
        cin = f
    fwd += 2 * 256 * 256 + 2 * 256 * 16  # dense head
    analytic = 3 * fwd  # fwd + ~2x bwd
    assert 0.7 * analytic < got < 1.3 * analytic, (got, analytic)


def test_pipelined_ceiling_caps_and_flags(monkeypatch):
    """A ceiling run that exceeds its time cap must return what it
    measured, flagged 'capped' (a silently depressed ceiling would
    publish utilization_vs_ceiling > 1 as if live beat the runtime).

    Bench-shape constants are shrunk for the CPU mesh (the cap logic is
    shape-independent; full 640x480 CPU convs would cost ~6 min)."""
    import bench

    monkeypatch.setattr(bench, "SHAPE", (64, 64))
    monkeypatch.setattr(bench, "BATCH", 8)
    out = bench.measure_pipelined_ceiling(2, items=32, time_cap=0.0)
    assert out["images"] > 0 and out["img_s"] > 0
    assert out.get("capped") is True
