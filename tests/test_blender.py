"""Opt-in real-Blender integration tier (VERDICT r1 item 2).

Every test here spawns a REAL Blender process through the production
``BlenderLauncher`` path against a paired ``tests/blender/*.blend.py``
producer — the reference's entire test identity
(``tests/test_launcher.py:20-44`` + ``tests/blender/*.blend.py``; CI via
``scripts/install_blender.sh``). The hermetic sim tier covers the same
consumer code paths without Blender; this tier is what first executes
``finder.py``, ``bpy_engine.py``, and the Blender halves of the producer
package.

Run:  scripts/install_blender.sh && source .envs
      blender --background --python scripts/install_producer.py
      pytest tests -m blender
Tests skip (not fail) when no usable Blender is on PATH.
"""

import os

import numpy as np
import pytest

from blendjax.launcher.finder import discover_blender

BLENDER = discover_blender()
pytestmark = [
    pytest.mark.blender,
    pytest.mark.skipif(
        BLENDER is None,
        reason="no usable Blender on PATH (scripts/install_blender.sh)",
    ),
]

FIXTURES = os.path.join(os.path.dirname(__file__), "blender")


def _script(name: str) -> str:
    return os.path.join(FIXTURES, name)


def test_blender_launcher_handshake():
    """Two instances get distinct btids/seeds/addresses and per-instance
    remainder args (reference ``test_launcher.py:20-44``)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher

    with BlenderLauncher(
        script=_script("launcher.blend.py"),
        background=True,
        num_instances=2,
        named_sockets=["DATA"],
        seed=10,
        instance_args=[["--x", "a"], ["--x", "b"]],
    ) as launcher:
        got = {}
        for msg in RemoteStream(
            launcher.addresses["DATA"], timeoutms=60_000, max_items=2
        ):
            got[msg["btid"]] = msg
    assert sorted(got) == [0, 1]
    assert [got[i]["btseed"] for i in (0, 1)] == [10, 11]
    assert got[0]["remainder"] == ["--x", "a"]
    assert got[1]["remainder"] == ["--x", "b"]
    for i in (0, 1):
        assert got[i]["btsockets"] == ["DATA"]


def test_blender_stream_ingest():
    """A real Blender animation loop streams 16 (64, 64) frames into the
    pipeline's host ingest (reference ``test_dataset.py:11-33``)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher

    with BlenderLauncher(
        script=_script("dataset.blend.py"),
        background=True,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
    ) as launcher:
        frames = []
        for msg in RemoteStream(
            launcher.addresses["DATA"], timeoutms=60_000, max_items=16
        ):
            assert msg["img"].shape == (64, 64)
            assert (msg["img"] == msg["frameid"] % 251).all()
            frames.append(int(msg["frameid"]))
    # 4 episodes x frames 1..4
    assert sorted(frames) == sorted(list(range(1, 5)) * 4)


def test_blender_duplex_echo():
    """Duplex echo incl. btid/btmid stamping (reference
    ``test_duplex.py:9-47``)."""
    from blendjax.launcher import BlenderLauncher
    from blendjax.transport.channels import PairChannel

    with BlenderLauncher(
        script=_script("duplex.blend.py"),
        background=True,
        num_instances=1,
        named_sockets=["CTRL"],
        seed=0,
    ) as launcher:
        duplex = PairChannel(
            launcher.addresses["CTRL"][0], btid=99, bind=False
        )
        try:
            mid = duplex.send(hello=[1, 2, 3])
            echo = duplex.recv(timeoutms=60_000)
            end = duplex.recv(timeoutms=60_000)
        finally:
            duplex.close()
    assert echo["echo"]["hello"] == [1, 2, 3]
    assert echo["echo"]["btid"] == 99
    assert echo["echo"]["btmid"] == mid
    assert echo["btid"] == 0  # producer stamp
    assert end["msg"] == "end"


def test_blender_animation_lifecycle():
    """Signal ordering over two episodes of frames 1..3 (reference
    ``test_animation.py:7-26``)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher

    with BlenderLauncher(
        script=_script("anim.blend.py"),
        background=True,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
    ) as launcher:
        (msg,) = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=1
            )
        )
    episode = (
        ["pre_animation"]
        + [s for f in (1, 2, 3) for s in (f"pre_frame:{f}", f"post_frame:{f}")]
        + ["post_animation"]
    )
    assert msg["seq"] == ["pre_play"] + episode * 2 + ["post_play"]


def test_blender_remote_env():
    """reset/step/reward/done across two episodes against a real Blender
    physics loop (reference ``test_env.py:12-43``)."""
    from blendjax.env.remote import RemoteEnv
    from blendjax.launcher import BlenderLauncher

    with BlenderLauncher(
        script=_script("env.blend.py"),
        background=True,
        num_instances=1,
        named_sockets=["GYM"],
        seed=0,
        instance_args=[["--done-after", "5"]],
    ) as launcher:
        env = RemoteEnv(launcher.addresses["GYM"][0], timeoutms=60_000)
        try:
            for _ in range(2):  # two episodes
                obs, info = env.reset()
                assert obs == pytest.approx(0.0)
                done = False
                steps = 0
                while not done:
                    obs, reward, done, info = env.step(0.6)
                    assert obs == pytest.approx(0.6)
                    assert reward == pytest.approx(1.0)
                    steps += 1
                    assert steps < 50
                assert steps >= 1
        finally:
            env.close()


def test_blender_camera_projection():
    """bpy-derived Camera (camera_from_bpy) projects identically to the
    standalone analytic camera rebuilt from the published pose (reference
    ``test_camera.py:10-49`` against the cam.blend scene)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher
    from blendjax.producer.camera import Camera

    with BlenderLauncher(
        script=_script("cam.blend.py"),
        background=True,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
    ) as launcher:
        (msg,) = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=1
            )
        )
    xyz = msg["xyz"]
    assert xyz.shape == (8, 3)

    pose = np.asarray(msg["proj_pose"])
    cam = Camera(
        position=pose[:3, 3], rotation=pose[:3, :3], shape=(480, 640),
        focal_mm=50.0, sensor_mm=36.0, clip_near=0.1, clip_far=100.0,
    )
    pix, z = cam.world_to_pixel(xyz, return_depth=True)
    np.testing.assert_allclose(pix, msg["proj_xy"], atol=1e-2)
    np.testing.assert_allclose(z, msg["proj_z"], atol=1e-4)

    pose_o = np.asarray(msg["ortho_pose"])
    cam_o = Camera(
        position=pose_o[:3, 3], rotation=pose_o[:3, :3], shape=(480, 640),
        ortho_scale=12.0, clip_near=0.1, clip_far=100.0,
    )
    pix_o, z_o = cam_o.world_to_pixel(xyz, return_depth=True)
    np.testing.assert_allclose(pix_o, msg["ortho_xy"], atol=1e-2)
    np.testing.assert_allclose(z_o, msg["ortho_z"], atol=1e-4)
    # the cube sits above/below: ortho depths are all ~10 - z_world
    np.testing.assert_allclose(z_o, 10.0 - xyz[:, 2], atol=1e-4)