"""Hermetic execution of the Blender-facing producer surface (VERDICT r2
item 2): ``bpy_engine.py`` and ``offscreen.py`` run in-process against
the fake ``bpy``/``gpu`` runtime (``blendjax.testing``). The opt-in
real-Blender tier (``test_blender.py``) remains the convention ground
truth; this tier keeps the code executed in every CI run."""

import math

import numpy as np
import pytest

from blendjax.testing import install_fake_bpy, reset_fake_bpy


@pytest.fixture()
def bpy():
    mod = install_fake_bpy(background=False)
    reset_fake_bpy()
    return mod


def _add_cube(bpy, size=2.0, location=(0, 0, 0), name=None):
    bpy.ops.mesh.primitive_cube_add(size=size, location=location)
    obj = bpy.context.active_object
    if name:
        obj.name = name
    return obj


def _add_camera(bpy, name="Cam", location=(0, 0, 10), rotation=(0, 0, 0),
                **props):
    data = bpy.data.cameras.new(name)
    for k, v in props.items():
        setattr(data, k, v)
    obj = bpy.data.objects.new(name, data)
    bpy.context.collection.objects.link(obj)
    obj.location = location
    obj.rotation_euler = rotation
    return obj


def test_world_and_bbox_coordinates(bpy):
    """world_coordinates/bbox_world_coordinates resolve the evaluated
    depsgraph path: local verts x matrix_world (reference
    ``utils.py:30-109``)."""
    from blendjax.producer.bpy_engine import (
        bbox_world_coordinates,
        world_coordinates,
    )

    cube = _add_cube(bpy, size=2.0, location=(0.5, -0.25, 0.75))
    xyz = world_coordinates(cube)
    assert xyz.shape == (8, 3)
    lo, hi = xyz.min(0), xyz.max(0)
    np.testing.assert_allclose(lo, [-0.5, -1.25, -0.25], atol=1e-12)
    np.testing.assert_allclose(hi, [1.5, 0.75, 1.75], atol=1e-12)
    # bbox corners are the same 8 points for an axis-aligned cube
    bb = bbox_world_coordinates(cube)
    assert bb.shape == (8, 3)
    np.testing.assert_allclose(
        np.sort(bb, axis=0), np.sort(xyz, axis=0), atol=1e-12
    )
    # rotation flows through matrix_world
    cube.rotation_euler[2] = math.pi / 2
    rot = world_coordinates(cube)
    np.testing.assert_allclose(
        np.sort(rot[:, 2]), np.sort(xyz[:, 2]), atol=1e-12
    )
    assert not np.allclose(rot[:, :2], xyz[:, :2])


def test_scene_stats_and_collections(bpy):
    from blendjax.producer.bpy_engine import scene_stats

    base = scene_stats()
    _add_cube(bpy)
    _add_camera(bpy)
    stats = scene_stats()
    assert stats["num_objects"] == base["num_objects"] + 2
    assert stats["num_meshes"] == base["num_meshes"] + 1
    assert "Cube" in bpy.data.objects


def test_visibility_montecarlo_with_occluder(bpy):
    """compute_object_visibility: unobstructed -> 1.0; a blocker between
    object and camera drops it to 0 (reference ``utils.py:158-179``)."""
    from blendjax.producer.bpy_engine import compute_object_visibility

    target = _add_cube(bpy, size=1.0, location=(0, 0, 0), name="Target")
    cam = _add_camera(bpy, location=(0, 0, 10))
    rng = np.random.default_rng(0)
    vis = compute_object_visibility(target, cam, n_samples=16, rng=rng)
    assert vis == pytest.approx(1.0)
    # a cube between target and camera blocks every corner's ray (all
    # rays converge toward the camera axis by z=5)
    _add_cube(bpy, size=1.0, location=(0, 0, 5), name="Blocker")
    vis = compute_object_visibility(target, cam, n_samples=16, rng=rng)
    assert vis == pytest.approx(0.0)


def test_camera_from_bpy_matches_analytic(bpy):
    """camera_from_bpy pulls pose/intrinsics from bpy and projects like a
    directly-constructed Camera (reference ``camera.py:8-82``)."""
    from blendjax.producer.bpy_engine import camera_from_bpy
    from blendjax.producer.camera import Camera

    bpy.context.scene.render.resolution_x = 640
    bpy.context.scene.render.resolution_y = 480
    cam_obj = _add_camera(
        bpy, location=(8.0, -8.0, 6.0),
        rotation=(math.radians(60), 0.0, math.radians(45)),
        lens=50.0, sensor_width=36.0, clip_start=0.1, clip_end=100.0,
    )
    cam = camera_from_bpy(Camera, cam_obj)
    assert cam.shape == (480, 640)
    pose = np.asarray(cam_obj.matrix_world)
    direct = Camera(
        position=pose[:3, 3], rotation=pose[:3, :3], shape=(480, 640),
        focal_mm=50.0, sensor_mm=36.0, clip_near=0.1, clip_far=100.0,
    )
    pts = np.array([[0.5, -0.25, 0.75], [0, 0, 0], [1, 1, 1.0]])
    np.testing.assert_allclose(
        cam.world_to_pixel(pts), direct.world_to_pixel(pts), atol=1e-12
    )
    # resolution_percentage scales the derived shape (camera.py:57-66)
    bpy.context.scene.render.resolution_percentage = 50
    half = camera_from_bpy(Camera, cam_obj)
    assert half.shape == (240, 320)
    # ortho branch
    cam_obj.data.type = "ORTHO"
    cam_obj.data.ortho_scale = 12.0
    bpy.context.scene.render.resolution_percentage = 100
    ortho = camera_from_bpy(Camera, cam_obj)
    assert ortho.ortho_scale == pytest.approx(12.0)


def test_bpy_engine_reset_syncs_point_cache(bpy):
    """BpyEngine.reset rewinds to frame_start and keeps rigid-body point
    caches in range (reference ``animation.py:108-134``)."""
    from types import SimpleNamespace

    from blendjax.producer.bpy_engine import BpyEngine

    scene = bpy.context.scene
    scene.frame_start, scene.frame_end = 3, 9
    scene.rigidbody_world = SimpleNamespace(
        point_cache=SimpleNamespace(frame_start=1, frame_end=250)
    )
    eng = BpyEngine()
    eng.frame_set(7)
    assert scene.frame_current == 7
    eng.reset()
    assert scene.frame_current == 3
    assert scene.rigidbody_world.point_cache.frame_start == 3
    assert scene.rigidbody_world.point_cache.frame_end == 9


def test_find_first_view3d_background_raises():
    from blendjax.producer.bpy_engine import find_first_view3d

    install_fake_bpy(background=False)
    reset_fake_bpy(background=True)  # --background: no windows
    with pytest.raises(RuntimeError, match="VIEW_3D"):
        find_first_view3d()
    reset_fake_bpy(background=False)
    assert find_first_view3d().type == "VIEW_3D"


def test_animation_driver_ui_lifecycle(bpy):
    """BpyAnimationDriver replays the controller lifecycle from Blender's
    own clock (frame_change_pre + POST_PIXEL draw handler, reference
    ``animation.py:136-151``): two 3-frame episodes, then cancel."""
    from blendjax.producer import AnimationController
    from blendjax.producer.bpy_engine import BpyAnimationDriver, BpyEngine

    ctrl = AnimationController(BpyEngine())
    driver = BpyAnimationDriver(ctrl)
    seq = []
    ctrl.pre_play.add(lambda: seq.append("pre_play"))
    ctrl.pre_animation.add(lambda: seq.append("pre_animation"))
    ctrl.pre_frame.add(lambda f: seq.append(f"pre:{f}"))
    ctrl.post_frame.add(lambda f: seq.append(f"post:{f}"))

    def on_episode_end():
        seq.append("post_animation")
        if ctrl.episode >= 1:  # episode increments after this signal
            driver.cancel()

    ctrl.post_animation.add(on_episode_end)
    ctrl.post_play.add(lambda: seq.append("post_play"))
    driver.play(frame_range=(1, 3))  # synchronous under the fake clock

    frames = [s for f in (1, 2, 3) for s in (f"pre:{f}", f"post:{f}")]
    assert seq == (
        ["pre_play", "pre_animation"]
        + frames + ["post_animation"]
        + frames + ["post_animation", "post_play"]
    )
    assert ctrl.episode == 2
    # handlers were unhooked by cancel
    assert not bpy.app.handlers.frame_change_pre


def test_offscreen_renderer_reads_back_and_flips(bpy):
    """OffScreenRenderer: GPUOffScreen draw + texture readback lands cube
    splats where the analytic Camera projects them, and 'upper-left'
    origin is the vertical flip of GL's lower-left scanlines (reference
    ``offscreen.py:68-99``)."""
    from blendjax.producer.bpy_engine import camera_from_bpy
    from blendjax.producer.camera import Camera
    from blendjax.producer.offscreen import OffScreenRenderer
    from blendjax.testing.fake_gpu import BACKGROUND

    render = bpy.context.scene.render
    render.resolution_x, render.resolution_y = 160, 120
    cube = _add_cube(bpy, size=2.0, location=(0, 0, 0))
    cam_obj = _add_camera(
        bpy, location=(0, -8, 0), rotation=(math.pi / 2, 0, 0),
        lens=35.0, clip_start=0.1, clip_end=100.0,
    )
    bpy.context.scene.camera = cam_obj

    r = OffScreenRenderer(mode="rgba", origin="upper-left")
    img = r.render()
    assert img.shape == (120, 160, 4) and img.dtype == np.uint8
    splats = np.argwhere((img != np.array(BACKGROUND)).any(-1))
    assert 1 <= len(splats) <= 8  # 8 cube corners, some may overlap

    # cross-check against the analytic camera (upper-left pixel origin)
    from blendjax.producer.bpy_engine import world_coordinates

    cam = camera_from_bpy(Camera, cam_obj)
    expected = cam.world_to_pixel(world_coordinates(cube))
    exp_yx = np.stack([expected[:, 1], expected[:, 0]], -1)
    for y, x in splats:
        d = np.linalg.norm(exp_yx - np.array([y, x]), axis=1)
        assert d.min() < 2.0, f"splat ({y},{x}) far from projections"

    r_ll = OffScreenRenderer(mode="rgba", origin="lower-left")
    np.testing.assert_array_equal(np.flipud(r_ll.render()), img)

    # Legacy-Blender path (no GPUOffScreen.texture_color): the GL
    # readback fallback produces the same frame (reference counterpart:
    # the glGetTexImage dance, ``btb/offscreen.py:68-99``).
    import sys as _sys
    import types as _types

    r_old = OffScreenRenderer(mode="rgba", origin="upper-left")
    pixels = r_old.offscreen._pixels  # the fake GPU's GL-ordered store

    def fake_read_pixels(x, y, w_, h_, fmt, dtype, buf):
        np.asarray(buf).reshape(h_, w_, 4)[:] = pixels

    gl_mod = _types.SimpleNamespace(
        GL=_types.SimpleNamespace(
            GL_RGBA=0x1908, GL_UNSIGNED_BYTE=0x1401,
            glReadPixels=fake_read_pixels,
        )
    )
    del r_old.offscreen.texture_color
    saved = _sys.modules.get("OpenGL")
    _sys.modules["OpenGL"] = gl_mod
    _sys.modules["OpenGL.GL"] = gl_mod.GL
    try:
        np.testing.assert_array_equal(r_old.render(), img)
    finally:
        _sys.modules.pop("OpenGL.GL", None)
        if saved is None:
            _sys.modules.pop("OpenGL", None)
        else:  # pragma: no cover
            _sys.modules["OpenGL"] = saved

    # rgb mode drops alpha
    r_rgb = OffScreenRenderer(mode="rgb")
    assert r_rgb.render().shape == (120, 160, 3)
    r_rgb.set_render_style(shading="RENDERED", overlays=False)
    assert r_rgb.space.shading.type == "RENDERED"
    assert r_rgb.space.overlay.show_overlays is False
