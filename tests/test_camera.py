"""Camera projection math against analytic ground truth (replaces the
reference's ``tests/test_camera.py`` + ``cam.blend`` fixture: same
assertions — pixel coords and depths for ortho and perspective cameras —
without needing a Blender scene)."""

import numpy as np

from blendjax.producer.camera import Camera
from blendjax.producer.utils import dehom, hom, look_at_matrix, random_spherical_loc


def test_ortho_projection_ground_truth():
    cam = Camera(
        position=(0, 0, 10),
        rotation=np.eye(3),  # looks down -Z
        shape=(100, 100),
        ortho_scale=4.0,
    )
    px, depth = cam.world_to_pixel(
        [[0, 0, 0], [1, 0, 0], [0, 1, 0], [-1, -1, 0]], return_depth=True
    )
    np.testing.assert_allclose(px[0], [50, 50], atol=1e-6)
    np.testing.assert_allclose(px[1], [75, 50], atol=1e-6)
    np.testing.assert_allclose(px[2], [50, 25], atol=1e-6)  # +y is up
    np.testing.assert_allclose(px[3], [25, 75], atol=1e-6)
    np.testing.assert_allclose(depth, [10, 10, 10, 10], atol=1e-9)


def test_perspective_projection_ground_truth():
    f, s = 50.0, 36.0
    cam = Camera(
        position=(0, 0, 5), shape=(100, 100), focal_mm=f, sensor_mm=s
    )
    px, depth = cam.world_to_pixel(
        [[0, 0, 0], [1, 0, 0]], return_depth=True
    )
    np.testing.assert_allclose(px[0], [50, 50], atol=1e-6)
    ndc_x = (2 * f / s * 1.0) / 5.0
    np.testing.assert_allclose(px[1, 0], (ndc_x + 1) * 0.5 * 100, atol=1e-6)
    np.testing.assert_allclose(depth, [5, 5], atol=1e-9)
    # farther object projects closer to the image center
    px2 = cam.world_to_pixel([[1, 0, -5]])
    assert abs(px2[0, 0] - 50) < abs(px[1, 0] - 50)


def test_lower_left_origin():
    cam = Camera(position=(0, 0, 10), shape=(100, 200), ortho_scale=4.0)
    up_world = [[0, 0.5, 0]]
    ul = cam.world_to_pixel(up_world, origin="upper-left")
    ll = cam.world_to_pixel(up_world, origin="lower-left")
    np.testing.assert_allclose(ul[0, 1] + ll[0, 1], 100, atol=1e-6)
    assert ll[0, 1] > 50  # up is larger y in lower-left origin


def test_look_at_points_camera_at_target():
    eye = np.array([4.0, -7.0, 3.0])
    cam = Camera.look_at(eye=eye, target=(0, 0, 0), shape=(200, 300))
    px, depth = cam.world_to_pixel([[0, 0, 0]], return_depth=True)
    np.testing.assert_allclose(px[0], [150, 100], atol=1e-6)
    np.testing.assert_allclose(depth[0], np.linalg.norm(eye), atol=1e-9)
    # rotation is orthonormal
    r = cam.rotation
    np.testing.assert_allclose(r @ r.T, np.eye(3), atol=1e-12)


def test_bbox_world_to_pixel():
    cam = Camera(position=(0, 0, 10), shape=(100, 100), ortho_scale=4.0)
    pts = [[-1, -1, 0], [1, 1, 0], [0, 0, 0]]
    bbox = cam.bbox_world_to_pixel(pts)
    np.testing.assert_allclose(bbox, [25, 25, 75, 75], atol=1e-6)


def test_hom_dehom_roundtrip():
    x = np.random.default_rng(0).normal(size=(7, 3))
    np.testing.assert_allclose(dehom(hom(x)), x, atol=1e-12)


def test_random_spherical_loc_in_shell():
    rng = np.random.default_rng(1)
    center = np.array([1.0, 2.0, 3.0])
    for _ in range(50):
        p = random_spherical_loc(
            radius_range=(2, 3), center=center, rng=rng
        )
        r = np.linalg.norm(p - center)
        assert 2 - 1e-9 <= r <= 3 + 1e-9


def test_look_at_degenerate_up():
    # looking straight down the up vector must not produce NaNs
    m = look_at_matrix((0, 0, 5), (0, 0, 0), up=(0, 0, 1))
    assert np.isfinite(m).all()
    np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-9)
