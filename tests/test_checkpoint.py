"""blendjax.checkpoint: async sharded snapshots, pickle-free session
state, preemption wiring — plus coverage for the orbax-backed
``blendjax.train.CheckpointManager`` wrapper (ISSUE 12).

The resume-equality acceptance contract (kill -9 -> resume ->
identical f32 trajectory, single-chip AND mesh, incl. elastic 8->4)
lives in ``tests/test_resume.py``; this file pins the building blocks:
format roundtrips, shard-walking saves, clone-before-donate safety,
bitwise-continuable session state per component, and the watchdog /
SIGTERM arms.
"""

import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

import jax

from blendjax.checkpoint import (
    PreemptionGuard,
    PreemptionRequested,
    SnapshotManager,
    collect_session,
    pack_session,
    restore_session,
    unpack_session,
)
from blendjax.models import CubeRegressor
from blendjax.parallel import batch_sharding, create_mesh
from blendjax.train import TrainDriver, make_supervised_step, make_train_state
from blendjax.utils.metrics import metrics as reg

B = 8
HW = 16


def _mesh(n):
    return create_mesh({"data": n}, devices=jax.devices()[:n])


def _batches(n, seed=0, batch=B):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        yield {
            "image": rng.integers(0, 255, (batch, HW, HW, 4), np.uint8),
            "xy": (rng.random((batch, 8, 2)) * HW).astype(np.float32),
        }


def _state(mesh=None):
    return make_train_state(
        CubeRegressor(features=(8,)), np.zeros((B, HW, HW, 4), np.uint8),
        mesh=mesh,
    )


# -- session codec ------------------------------------------------------------


def test_session_codec_roundtrip():
    doc = {
        "arr": np.arange(12, dtype=np.float32).reshape(3, 4),
        "flags": np.array([True, False]),
        "big": 2**100,  # PCG64 state words are 128-bit
        "neg_big": -(2**80),
        "rng": np.random.default_rng(3).bit_generator.state,
        "nested": {"l": [1, 2.5, "x", None, b"raw"], 7: "int-key"},
    }
    out = unpack_session(pack_session(doc))
    assert np.array_equal(out["arr"], doc["arr"])
    assert out["arr"].dtype == np.float32
    assert np.array_equal(out["flags"], doc["flags"])
    assert out["big"] == 2**100 and out["neg_big"] == -(2**80)
    assert out["nested"]["l"] == [1, 2.5, "x", None, b"raw"]
    assert out["nested"][7] == "int-key"
    # the decoded rng state actually drives a Generator
    g = np.random.default_rng(0)
    g.bit_generator.state = out["rng"]
    ref = np.random.default_rng(3)
    assert g.random() == ref.random()


def test_session_codec_is_pickle_free():
    class Opaque:
        pass

    with pytest.raises(TypeError, match="pickle"):
        pack_session({"bad": Opaque()})
    with pytest.raises(ValueError, match="reserved"):
        pack_session({"__nd__": 1})
    with pytest.raises(TypeError, match="object dtype"):
        pack_session({"o": np.array([object()])})


# -- snapshot manager ---------------------------------------------------------


def test_snapshot_roundtrip_walks_shards_and_preserves_shardings(tmp_path):
    mesh = _mesh(8)
    sharded = jax.device_put(
        np.arange(64, dtype=np.float32).reshape(8, 8),
        batch_sharding(mesh),
    )
    replicated = jax.device_put(
        np.ones((3,), np.float32),
        jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec()),
    )
    state = {"w": sharded, "b": replicated, "step": 4}
    with SnapshotManager(str(tmp_path), keep=3) as mgr:
        mgr.save_async(4, state)
        mgr.wait()
        assert mgr.steps() == [4]
        # per-addressable-shard writes: the data-sharded leaf wrote 8
        # shard files, the replicated one deduped to 1 (replica_id 0)
        with open(os.path.join(
            str(tmp_path), "step-00000004", "manifest.json"
        )) as f:
            manifest = json.load(f)
        shard_counts = {
            e["path"]: len(e.get("shards", []))
            for e in manifest["leaves"]
        }
        assert shard_counts["['w']"] == 8
        assert shard_counts["['b']"] == 1
        template = {
            "w": jax.device_put(np.zeros((8, 8), np.float32),
                                batch_sharding(mesh)),
            "b": jax.device_put(
                np.zeros((3,), np.float32),
                jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec()
                ),
            ),
            "step": 0,
        }
        res = mgr.restore(template)
        assert res.step == 4 and res.state["step"] == 4
        assert np.array_equal(np.asarray(res.state["w"]),
                              np.asarray(sharded))
        assert res.state["w"].sharding == template["w"].sharding
        assert not res.resharded


def test_restore_on_empty_dir_returns_none(tmp_path):
    with SnapshotManager(str(tmp_path)) as mgr:
        assert mgr.restore(_state()) is None
        assert mgr.latest_step() is None


def test_elastic_restore_onto_smaller_mesh_counts_resharded(tmp_path):
    reg.reset()
    mesh8 = _mesh(8)
    state = {"ring": jax.device_put(
        np.arange(128, dtype=np.float32).reshape(8, 16),
        batch_sharding(mesh8),
    )}
    with SnapshotManager(str(tmp_path)) as mgr:
        mgr.save_async(1, state)
        mgr.wait()
        mesh4 = _mesh(4)
        template = {"ring": jax.device_put(
            np.zeros((8, 16), np.float32), batch_sharding(mesh4)
        )}
        res = mgr.restore(template)
    assert np.array_equal(np.asarray(res.state["ring"]),
                          np.arange(128, dtype=np.float32).reshape(8, 16))
    assert len(res.state["ring"].sharding.device_set) == 4
    assert res.resharded
    assert reg.report()["counters"]["ckpt.resharded_restores"] == 1


def test_async_save_survives_subsequent_donation(tmp_path):
    """The clone-before-donate contract: a snapshot taken between two
    steps restores the state AS OF the snapshot, even though the very
    next dispatch donated (and overwrote) the live buffers."""
    state = _state()
    step = make_supervised_step()
    batches = list(_batches(4, seed=1))
    state, _ = step(state, batches[0])
    ref = jax.tree.map(np.asarray, jax.device_get(state.params))
    with SnapshotManager(str(tmp_path)) as mgr:
        mgr.save_async(1, state)
        for b in batches[1:]:  # donate the live state repeatedly
            state, _ = step(state, b)
        mgr.wait()
        res = mgr.restore(_state())
    restored = jax.tree.map(np.asarray, jax.device_get(res.state.params))
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(restored)):
        assert np.array_equal(a, b)
    # and the live state did move on
    live = jax.tree.leaves(jax.device_get(state.params))
    assert not all(
        np.array_equal(a, b)
        for a, b in zip(jax.tree.leaves(ref), live)
    )


def test_retention_prunes_and_tmp_sweep(tmp_path):
    state = {"w": np.ones((2,), np.float32)}
    with SnapshotManager(str(tmp_path), keep=2) as mgr:
        for s in range(1, 6):
            mgr.save_async(s, state)
            mgr.wait()
        assert mgr.steps() == [4, 5]
    # a kill -9 mid-write leaves a .tmp- stage; the next manager sweeps
    stale = tmp_path / ".tmp-00000009-123"
    stale.mkdir()
    (stale / "garbage.bin").write_bytes(b"x")
    mgr2 = SnapshotManager(str(tmp_path))
    assert not stale.exists()
    assert mgr2.steps() == [4, 5]
    mgr2.close()


def test_writer_backpressure_replaces_pending(tmp_path):
    """A slow disk degrades cadence, never accumulates device clones:
    the pending slot holds ONE snapshot and a newer save replaces it."""
    reg.reset()
    state = {"w": np.ones((2,), np.float32)}
    mgr = SnapshotManager(str(tmp_path))
    # stall the writer by grabbing its condition before any save
    with mgr._cv:
        mgr._pending = (1, state, {})
        mgr._ensure_thread()
    mgr.save_async(2, state)  # replaces queued step 1
    mgr.wait()
    assert mgr.steps() == [2]
    assert reg.report()["counters"]["ckpt.skipped"] == 1
    mgr.close()


# -- driver integration -------------------------------------------------------


def test_driver_checkpoint_cadence_keeps_one_dispatch_per_step(tmp_path):
    reg.reset()
    state = _state()
    drv = TrainDriver(
        make_supervised_step(), state, inflight=2, sync_every=1,
        checkpoint=SnapshotManager(str(tmp_path)), checkpoint_every=2,
        session_state=lambda: {"custom": {"mark": 1}},
    )
    for b in _batches(6, seed=2):
        drv.submit(b)
    drv.finish()
    drv.checkpoint.wait()
    report = reg.report()
    assert drv.checkpoints == 3
    # every cadence point was handed to the manager; a fast step loop
    # may legitimately outrun the writer, in which case the bounded
    # pending slot REPLACES a queued snapshot (ckpt.skipped) rather
    # than accumulating device clones — the newest cadence point
    # always commits
    committed = drv.checkpoint.steps()
    assert set(committed) <= {2, 4, 6} and committed[-1] == 6
    counters = report["counters"]
    assert counters["ckpt.saves"] + counters.get("ckpt.skipped", 0) == 3
    # the structural contract: checkpointing added ZERO train dispatches
    # and the save wall time landed on the writer thread's histogram
    assert report["spans"]["train.dispatch"]["count"] == 6
    assert report["histograms"]["ckpt.save_ms"]["count"] == len(committed)
    res = drv.checkpoint.restore(_state())
    assert res.session["custom"] == {"mark": 1}
    assert res.session["driver"]["steps"] == 6
    drv.checkpoint.close()


def test_request_checkpoint_lands_at_next_step_boundary(tmp_path):
    state = _state()
    drv = TrainDriver(
        make_supervised_step(), state, inflight=1, sync_every=0,
        checkpoint=SnapshotManager(str(tmp_path)), checkpoint_every=0,
    )
    batches = list(_batches(3, seed=3))
    drv.submit(batches[0])
    assert drv.checkpoints == 0  # no cadence configured
    drv.request_checkpoint()  # e.g. from the watchdog thread
    drv.submit(batches[1])
    assert drv.checkpoints == 1
    drv.submit(batches[2])
    assert drv.checkpoints == 1  # one request, one snapshot
    drv.finish()
    drv.checkpoint.wait()
    assert drv.checkpoint.steps() == [2]
    drv.checkpoint.close()


def test_driver_state_dict_roundtrip():
    state = _state()
    drv = TrainDriver(make_supervised_step(), state, sync_every=1)
    for b in _batches(3, seed=4):
        drv.submit(b)
    drv.finish()
    d = unpack_session(pack_session({"driver": drv.state_dict()}))
    drv2 = TrainDriver(make_supervised_step(), _state(), sync_every=1)
    drv2.load_state_dict(d["driver"])
    assert drv2.steps == drv.steps
    assert drv2.losses == drv.losses


# -- preemption ---------------------------------------------------------------


def test_sigterm_drains_snapshots_and_raises(tmp_path):
    state = _state()
    drv = TrainDriver(
        make_supervised_step(), state, inflight=2, sync_every=1,
        checkpoint=SnapshotManager(str(tmp_path)), checkpoint_every=0,
    )
    guard = PreemptionGuard(drv)
    try:
        batches = list(_batches(4, seed=5))
        drv.submit(batches[0])
        drv.submit(batches[1])
        os.kill(os.getpid(), signal.SIGTERM)
        # the handler only sets a flag; the drain + snapshot happen at
        # the next step boundary, where donated buffers have settled
        with pytest.raises(PreemptionRequested, match="committed"):
            drv.submit(batches[2])
    finally:
        guard.uninstall()
    drv.checkpoint.wait()
    assert drv.checkpoint.steps() == [2]
    res = drv.checkpoint.restore(_state())
    assert res.session["driver"]["steps"] == 2
    assert reg.counter_value("ckpt.preempt_signals") >= 1
    drv.checkpoint.close()


def test_preemption_guard_inert_off_main_thread():
    captured = {}

    def worker():
        captured["guard"] = PreemptionGuard(signals=(signal.SIGTERM,))

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    g = captured["guard"]
    assert g.installed is False
    g.request()  # programmatic preemption still works
    assert g.requested


def test_preempt_flush_reports_failed_snapshot(tmp_path):
    """The writer never raises into the train loop, so the preemption
    path must not report 'committed' on silence alone: a failed flush
    names the failure (the operator/scheduler would otherwise believe
    steps were preserved that are gone)."""
    state = _state()
    mgr = SnapshotManager(str(tmp_path))

    def boom(step, st, session):
        raise OSError(28, "No space left on device")

    mgr._write_one = boom
    drv = TrainDriver(
        make_supervised_step(), state, inflight=1, sync_every=1,
        checkpoint=mgr,
    )
    guard = PreemptionGuard(drv)
    try:
        batches = list(_batches(2, seed=8))
        drv.submit(batches[0])
        guard.request()
        with pytest.raises(PreemptionRequested, match="FAILED"):
            drv.submit(batches[1])
    finally:
        guard.uninstall()
    with pytest.raises(RuntimeError, match="write failed"):
        drv.checkpoint_now()
    mgr.close()


def test_driver_state_dict_bounds_loss_tail():
    drv = TrainDriver(make_supervised_step(), _state())
    drv.losses = [float(i) for i in range(drv.LOSS_TAIL + 100)]
    drv.steps = drv.dispatches = len(drv.losses)
    d = drv.state_dict()
    assert len(d["losses"]) == drv.LOSS_TAIL
    assert d["losses_total"] == drv.LOSS_TAIL + 100
    assert d["losses"][-1] == drv.losses[-1]


# -- component session state --------------------------------------------------


def _echo_batches(n, seed=0, batch=4):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        yield {
            "image": rng.integers(0, 255, (batch, HW, HW, 4), np.uint8),
            "xy": (rng.random((batch, 8, 2)) * HW).astype(np.float32),
        }


def test_echo_session_state_is_bitwise_continuable():
    """The headline determinism contract: a restored echo pipeline
    draws the SAME slots with the SAME augmentation keys the
    uninterrupted run would have — byte-identical batches."""
    from blendjax.data.echo import EchoingPipeline

    a = EchoingPipeline(
        list(_echo_batches(4, seed=9)), capacity=16, max_echo_factor=6,
        batch_size=4, rng=5,
    )
    it = iter(a)
    drawn = 0
    # consume until the inner stream is fully inserted (the _DONE
    # sentinel popped), so the snapshot and continuation see no
    # further insert timing
    deadline = time.monotonic() + 10
    while not (a._inner_done and a._queue.empty()):
        next(it)
        drawn += 1
        assert time.monotonic() < deadline
    sd_raw = a.state_dict()
    # the snapshot must be copies, not references: the draw loop keeps
    # mutating slot accounting while the writer thread serializes
    use_at_snapshot = sd_raw["use"].copy()
    sd = unpack_session(pack_session({"echo": sd_raw}))["echo"]
    cont = [next(it) for _ in range(3)]
    assert np.array_equal(sd_raw["use"], use_at_snapshot)

    b = EchoingPipeline(
        iter(()), capacity=16, max_echo_factor=6, batch_size=4, rng=5,
    )
    b.load_state_dict(sd)
    itb = iter(b)
    resumed = [next(itb) for _ in range(3)]
    for x, y in zip(cont, resumed):
        for k in ("image", "xy"):
            assert np.array_equal(np.asarray(x[k]), np.asarray(y[k]))
    assert b.steps == a.steps and b.fresh == a.fresh
    a.stop()
    b.stop()


def test_reservoir_state_dict_preserves_ring_and_counters():
    from blendjax.data.echo import SampleReservoir

    r = SampleReservoir(8, augment=None, rng=1)
    r.insert({"x": np.arange(12, dtype=np.float32).reshape(6, 2)})
    r.sample(np.array([0, 1]))
    sd = unpack_session(pack_session(r.state_dict()))
    r2 = SampleReservoir(8, augment=None, rng=1)
    r2.load_state_dict(sd)
    assert r2.size == r.size and r2._draws == r._draws
    assert np.array_equal(
        np.asarray(r2.gather(np.arange(6))["x"]),
        np.asarray(r.gather(np.arange(6))["x"]),
    )
    # the cursor continues: the next insert lands in the same slots
    s1 = r.insert({"x": np.ones((4, 2), np.float32)})
    s2 = r2.insert({"x": np.ones((4, 2), np.float32)})
    assert np.array_equal(s1, s2)
    with pytest.raises(ValueError, match="capacity"):
        SampleReservoir(4).load_state_dict(sd)


def test_scenario_ledger_roundtrip_preserves_windows_and_theta():
    from blendjax.scenario import ScenarioSpace
    from blendjax.scenario.accounting import ScenarioAccounting

    space = ScenarioSpace.parse("easy:half_extent=u(0.8,1.2) / "
                                "hard:xy_jitter=g(2,0.5)")
    led = ScenarioAccounting()
    led.declare(space)
    stamps = (
        [{"id": "easy", "ver": 1}] * 3
        + [{"id": "hard", "ver": 1, "theta": [1.5]}] * 2
        + [{"id": "hard", "ver": 2, "theta": [2.5]}]
    )
    led.observe_rows(stamps, fresh=[True] * 4 + [False] * 2)
    led.observe_loss(stamps, 0.25)
    sd = unpack_session(pack_session(led.state_dict()))
    led2 = ScenarioAccounting()
    led2.load_state_dict(sd)
    assert led2.totals() == led.totals()
    r1, r2 = led.report(), led2.report()
    assert r2["scenarios"]["hard"]["versions"] == {1: 2, 2: 1}
    assert r2["scenarios"]["easy"]["loss"]["count"] == 3
    assert r1["declared"] == r2["declared"]
    # the curriculum's evidence window survived the restart
    assert led2.window_losses(reset=False) == led.window_losses(
        reset=False
    )
    assert led2.theta_samples("hard", drain=False) == [
        ([1.5], 0.25), ([1.5], 0.25), ([2.5], 0.25)
    ]


def test_curriculum_roundtrip_restores_space_in_place():
    from blendjax.scenario import ScenarioCurriculum, ScenarioSpace
    from blendjax.scenario.accounting import ScenarioAccounting

    space = ScenarioSpace.parse(
        "easy:half_extent=u(0.8,1.2) / hard:xy_jitter=16"
    )
    led = ScenarioAccounting()
    cur = ScenarioCurriculum(
        space, ledger=led, every_steps=4, min_rows=2, adapt_params=False,
    )
    led.observe_rows([{"id": "easy", "ver": 1}] * 4
                     + [{"id": "hard", "ver": 1}] * 4)
    led.observe_loss([{"id": "easy", "ver": 1}] * 4, 0.1)
    led.observe_loss([{"id": "hard", "ver": 1}] * 4, 0.9)
    assert cur.update() is not None
    assert space.version == 2
    sd = unpack_session(pack_session(cur.state_dict()))

    space2 = ScenarioSpace.parse(
        "easy:half_extent=u(0.8,1.2) / hard:xy_jitter=16"
    )
    led2 = ScenarioAccounting()
    cur2 = ScenarioCurriculum(
        space2, ledger=led2, every_steps=4, min_rows=2,
        adapt_params=False,
    )
    cur2.load_state_dict(sd)
    # restored IN PLACE: same object, adapted weights, bumped version
    assert space2.version == 2
    assert space2.weights() == pytest.approx(space.weights())
    assert cur2.updates == 1 and led2.space_version == 2


def test_lineage_roundtrip_restart_is_not_a_gap_storm():
    from blendjax.obs.lineage import FrameLineage

    ln = FrameLineage()
    for seq in range(6):
        ln.ingest({"btid": 0, "_seq": seq, "_pub_wall": time.time()})
    sd = unpack_session(pack_session(ln.state_dict()))
    ln2 = FrameLineage()
    ln2.load_state_dict(sd)
    rep = ln2.report()["0"]
    assert rep["last_seq"] == 5 and rep["received"] == 6
    # consumer + producer restarted together: fresh numbering from 0
    # reads as a RESTART through the restored seq position, zero gaps
    ln2.ingest({"btid": 0, "_seq": 0, "_pub_wall": time.time()})
    rep = ln2.report()["0"]
    assert rep["restarts"] == 1 and rep["seq_gaps"] == 0
    # a producer that kept publishing while the consumer was down:
    # the missed frames are HONEST gaps against the restored position
    ln3 = FrameLineage()
    ln3.load_state_dict(sd)
    ln3.ingest({"btid": 0, "_seq": 9, "_pub_wall": time.time()})
    assert ln3.report()["0"]["seq_gaps"] == 3


def test_fleet_controller_state_roundtrip():
    from test_fleet import FakeConnector, FakeLauncher, FakeLineage

    from blendjax.fleet import FleetController, FleetPolicy

    ctrl = FleetController(
        FakeLauncher(3), FakeConnector(),
        policy=FleetPolicy(min_instances=1, max_instances=6),
        lineage=FakeLineage(),
    )
    ctrl.admit_remote("render-box", "tcp://127.0.0.1:9402")
    sd = unpack_session(pack_session(ctrl.state_dict()))
    assert sd == {
        "launched": 3, "remote": {"render-box": "tcp://127.0.0.1:9402"},
    }
    launcher2, conn2 = FakeLauncher(1), FakeConnector()
    ctrl2 = FleetController(
        launcher2, conn2,
        policy=FleetPolicy(min_instances=1, max_instances=6),
        lineage=FakeLineage(),
    )
    ctrl2.load_state_dict(sd)
    # grew back to the saved count and re-admitted the remote member
    assert launcher2.active_count() == 3
    assert ctrl2.remote == {"render-box": "tcp://127.0.0.1:9402"}
    assert "tcp://127.0.0.1:9402" in conn2.connected
    assert ctrl2.state()["instances"] == 4


def test_collect_and_restore_session_roundtrip():
    class Comp:
        def __init__(self):
            self.loaded = None

        def state_dict(self):
            return {"v": 7}

        def load_state_dict(self, d):
            self.loaded = d

    c = Comp()
    session = collect_session(comp=c, skipped=None,
                              stream={"consumed": 12})
    assert session["_version"] == 1
    out = unpack_session(pack_session(session))
    c2 = Comp()
    restored = restore_session(out, comp=c2)
    assert c2.loaded == {"v": 7} and restored == ["comp"]
    with pytest.raises(ValueError, match="no state for"):
        restore_session(out, strict=True, other=Comp())
    with pytest.raises(ValueError, match="newer"):
        restore_session({"_version": 99})


# -- watchdog arm -------------------------------------------------------------


def test_flight_recorder_checkpoint_on_breach_arm(tmp_path):
    from blendjax.obs.watchdog import FlightRecorder

    calls = []
    rec = FlightRecorder(
        str(tmp_path), checkpoint=lambda: calls.append(1) or {"ok": 1}
    )
    bundle = rec.dump(reason="test-breach")
    assert calls == [1]
    with open(os.path.join(bundle, "checkpoint.json")) as f:
        doc = json.load(f)
    assert doc["requested"] is True and doc["result"] == {"ok": 1}


def test_reporter_wires_checkpoint_on_breach(tmp_path):
    from blendjax.obs import StatsReporter

    drv_flag = []
    rep = StatsReporter(
        interval_s=60, slos=["gauge(test.always) >= 100"],
        flight_dir=str(tmp_path),
        checkpoint_on_breach=lambda: drv_flag.append(True),
    )
    reg.gauge("test.always", 1)  # breaches the floor immediately
    rep.tick()
    assert drv_flag == [True]
    bundles = [d for d in os.listdir(tmp_path) if d.startswith("flight-")]
    assert len(bundles) == 1
    assert os.path.exists(
        os.path.join(tmp_path, bundles[0], "checkpoint.json")
    )


# -- the orbax wrapper (optional extra) ---------------------------------------


def _has_orbax():
    try:
        import orbax.checkpoint  # noqa: F401

        return True
    except ImportError:
        return False


orbax_required = pytest.mark.skipif(
    not _has_orbax(), reason="orbax-checkpoint not installed (optional "
    "extra blendjax[orbax])",
)


def test_orbax_missing_raises_actionable_import_error(tmp_path,
                                                      monkeypatch):
    monkeypatch.setitem(sys.modules, "orbax", None)
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)
    from blendjax.train import CheckpointManager

    with pytest.raises(ImportError, match=r"blendjax\[orbax\]"):
        CheckpointManager(str(tmp_path))


@orbax_required
def test_orbax_save_restore_roundtrip(tmp_path):
    from blendjax.train import CheckpointManager, make_train_state

    state = _state()
    step = make_supervised_step()
    state, _ = step(state, next(_batches(1, seed=6)))
    mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
    mgr.save(1, state)
    mgr.wait()
    restored = mgr.restore(_state())
    assert restored is not None
    for a, b in zip(
        jax.tree.leaves(jax.device_get(state.params)),
        jax.tree.leaves(jax.device_get(restored.params)),
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


@orbax_required
def test_orbax_restore_on_empty_dir_returns_none(tmp_path):
    from blendjax.train import CheckpointManager

    mgr = CheckpointManager(str(tmp_path))
    assert mgr.restore(_state()) is None
    mgr.close()


@orbax_required
def test_orbax_sharded_restore_preserves_shardings(tmp_path):
    from blendjax.train import CheckpointManager

    mesh = _mesh(8)
    state = _state(mesh=mesh)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, state)
    mgr.wait()
    template = _state(mesh=mesh)
    restored = mgr.restore(template)
    la = jax.tree.leaves(template.params)[0]
    lb = jax.tree.leaves(restored.params)[0]
    assert lb.sharding.device_set == la.sharding.device_set
    mgr.close()


@orbax_required
def test_orbax_async_save_overlaps_subsequent_step(tmp_path):
    from blendjax.train import CheckpointManager

    state = _state()
    step = make_supervised_step()
    batches = list(_batches(3, seed=7))
    state, _ = step(state, batches[0])
    ref = jax.tree.map(np.asarray, jax.device_get(state.params))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, state)  # async: serialization overlaps the next steps
    # donating the state while orbax serializes would corrupt the
    # snapshot — train on with donate disabled, as documented
    step_nd = make_supervised_step(donate=False)
    for b in batches[1:]:
        state, _ = step_nd(state, b)
    mgr.wait()
    restored = mgr.restore(_state())
    for a, b in zip(jax.tree.leaves(ref),
                    jax.tree.leaves(jax.device_get(restored.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


def test_cross_layout_resume_f32_identical(tmp_path):
    """Cross-layout resume (the layout-system satellite): train under
    data×fsdp, snapshot, restore onto a pure-data mesh (counted as a
    resharded restore), and the continuation is f32-identical to the
    leg that never stopped."""
    from blendjax.parallel import resolve_layout
    from blendjax.train.mesh_driver import make_mesh_supervised_step

    reg.reset()
    img = np.zeros((B, HW, HW, 4), np.uint8)
    model = CubeRegressor(features=(8,), dtype=np.float32)
    mesh_f = resolve_layout("data2xfsdp4").create_mesh()
    state = make_train_state(
        model, img, mesh=mesh_f, layout="data2xfsdp4"
    )
    step_f = make_mesh_supervised_step(state, mesh_f)
    bs_f = batch_sharding(mesh_f)
    batches = list(_batches(4, seed=3))
    for b in batches[:2]:
        state, _ = step_f(
            state, {k: jax.device_put(v, bs_f) for k, v in b.items()}
        )
    with SnapshotManager(str(tmp_path)) as mgr:
        mgr.save_async(2, state)
        mgr.wait()
        mesh_d = _mesh(8)
        template = make_train_state(model, img, mesh=mesh_d)
        res = mgr.restore(template, mesh=mesh_d)
    assert res.resharded
    assert reg.report()["counters"]["ckpt.resharded_restores"] >= 1
    # every restored leaf landed on the pure-data mesh
    leaf = jax.tree_util.tree_leaves(res.state.params)[0]
    assert len(leaf.sharding.device_set) == 8
    # continue both legs on identical data: losses equal to f32
    # reduction rounding (cross-layout reordering, same program)
    step_d = make_mesh_supervised_step(res.state, mesh_d)
    bs_d = batch_sharding(mesh_d)
    st_f, st_d = state, res.state
    for b in batches[2:]:
        st_f, mf = step_f(
            st_f, {k: jax.device_put(v, bs_f) for k, v in b.items()}
        )
        st_d, md = step_d(
            st_d, {k: jax.device_put(v, bs_d) for k, v in b.items()}
        )
        np.testing.assert_allclose(
            np.asarray(mf["loss"]), np.asarray(md["loss"]),
            rtol=0, atol=5e-5,
        )
