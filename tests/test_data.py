"""Data pipeline tests: schema, record/replay, streaming, batching."""

import threading
import time

import numpy as np
import pytest

from blendjax.data import (
    BatchAssembler,
    FileDataset,
    FileReader,
    FileRecorder,
    HostIngest,
    RemoteStream,
    SingleFileDataset,
    StreamSchema,
)
from blendjax.data.schema import SchemaError
from blendjax.transport import DataPublisherSocket, ReceiveTimeoutError
from blendjax.transport.wire import encode_message

WILD = "tcp://127.0.0.1:*"


def _item(i, h=4, w=6):
    return {
        "btid": 0,
        "image": np.full((h, w, 4), i % 255, np.uint8),
        "xy": np.full((8, 2), float(i), np.float32),
        "frameid": i,
    }


# -- schema -----------------------------------------------------------------


def test_schema_infer_and_validate():
    schema = StreamSchema.infer(_item(1))
    assert set(schema.fields) == {"image", "xy", "frameid"}
    assert schema.fields["image"].shape == (4, 6, 4)
    assert schema.fields["frameid"].shape == ()
    schema.validate(_item(2))
    bad = _item(3)
    bad["image"] = bad["image"][:2]
    with pytest.raises(SchemaError, match="shape"):
        schema.validate(bad)
    bad2 = _item(3)
    bad2["xy"] = bad2["xy"].astype(np.float64)
    with pytest.raises(SchemaError, match="dtype"):
        schema.validate(bad2)
    with pytest.raises(SchemaError, match="missing"):
        schema.validate({"image": _item(0)["image"], "frameid": 1})


def test_schema_infers_string_as_meta():
    schema = StreamSchema.infer({**_item(0), "name": "cube"})
    assert "name" in schema.meta_keys and "name" not in schema.fields


# -- record / replay --------------------------------------------------------


def test_record_replay_roundtrip(tmp_path):
    path = str(tmp_path / "rec.bjr")
    with FileRecorder(path) as rec:
        for i in range(5):
            rec.save(encode_message(_item(i)))
    reader = FileReader(path)
    assert len(reader) == 5
    for i in (0, 3, 4, 1):  # random access
        msg = reader[i]
        assert msg["frameid"] == i
        np.testing.assert_array_equal(msg["image"], _item(i)["image"])
    # tensor-codec recordings replay with pickle disabled (safe sharing)
    safe = FileReader(path, allow_pickle=False)
    assert safe[2]["frameid"] == 2


def test_recorder_max_messages(tmp_path):
    path = str(tmp_path / "rec.bjr")
    with FileRecorder(path, max_messages=2) as rec:
        assert rec.save(encode_message(_item(0)))
        assert rec.save(encode_message(_item(1)))
        assert not rec.save(encode_message(_item(2)))
    assert len(FileReader(path)) == 2


def test_recover_truncated_recording(tmp_path):
    path = str(tmp_path / "crash.bjr")
    with FileRecorder(path) as rec:
        for i in range(4):
            rec.save(encode_message(_item(i)))
    data = open(path, "rb").read()
    # chop off footer + part of the last message
    open(path, "wb").write(data[: len(data) - 40 - 8 * 4 - 16 - 7])
    with pytest.raises(ValueError, match="footer"):
        FileReader(path)
    offsets = FileReader.recover(path)
    assert 1 <= len(offsets) <= 4


def _write_reference_btr(path, messages, capacity=16):
    """Write a recording in the reference blendtorch's EXACT ``.btr``
    format (``pkg_pytorch/blendtorch/btt/file.py:56-79``): ONE pickler
    (protocol 3, shared memo across documents) dumps a pre-allocated
    int64 offset header then each message; the header is rewritten with
    real offsets on close, -1 marking unused slots."""
    import pickle

    with open(path, "wb") as f:
        pickler = pickle.Pickler(f, protocol=3)
        offsets = np.full(capacity, -1, dtype=np.int64)
        pickler.dump(offsets)
        for i, msg in enumerate(messages):
            offsets[i] = f.tell()
            pickler.dump(msg)
        f.seek(0)
        pickle.Pickler(f, protocol=3).dump(offsets)


def test_legacy_btr_reader_roundtrip(tmp_path):
    """A reference-format .btr replays message-exactly, including RANDOM
    access (the single-pickler format embeds cross-message memo refs —
    repeated dict keys — that a naive seek-and-unpickle breaks on)."""
    from blendjax.data.replay import LegacyBtrReader

    path = str(tmp_path / "legacy_00.btr")
    msgs = [_item(i) for i in range(6)]
    _write_reference_btr(path, msgs)

    r = LegacyBtrReader(path, allow_pickle=True)
    assert len(r) == 6
    for i in (4, 0, 5, 2, 2, 1):  # out-of-order on purpose
        got = r[i]
        assert got["frameid"] == i
        np.testing.assert_array_equal(got["image"], msgs[i]["image"])
        np.testing.assert_array_equal(got["xy"], msgs[i]["xy"])
    r.close()
    # pickle gate: the format IS pickle, and the gate defaults closed —
    # both the explicit refusal and the untrusted default raise
    with pytest.raises(ValueError, match="pickle"):
        LegacyBtrReader(path, allow_pickle=False)
    with pytest.raises(ValueError, match="pickle"):
        LegacyBtrReader(path)


def test_legacy_btr_through_pipeline_and_datasets(tmp_path):
    """Reference recordings replay through StreamDataPipeline (VERDICT r2
    item 5) and glob side-by-side with .bjr in FileDataset."""
    from blendjax.data import StreamDataPipeline

    prefix = str(tmp_path / "mixed")
    _write_reference_btr(
        f"{prefix}_00.btr", [_item(i) for i in range(4)]
    )
    with FileRecorder(f"{prefix}_01.bjr") as rec:
        for i in range(2):
            rec.save(encode_message(_item(10 + i)))

    with StreamDataPipeline.from_recording(
        f"{prefix}_00.btr", batch_size=2, allow_pickle=True
    ) as pipe:
        batches = list(pipe)
    assert len(batches) == 2
    got = np.concatenate([np.asarray(b["frameid"]) for b in batches])
    np.testing.assert_array_equal(np.sort(got), np.arange(4))
    np.testing.assert_array_equal(
        np.asarray(batches[0]["image"][0]),
        _item(int(np.asarray(batches[0]["frameid"])[0]))["image"],
    )

    # globs *.bjr AND *.btr; the .btr half is pickle, so the mixed glob
    # needs the explicit trust opt-in (the default refuses to construct)
    with pytest.raises(ValueError, match="pickle"):
        FileDataset(prefix)
    ds = FileDataset(prefix, allow_pickle=True)
    assert len(ds) == 6
    assert (
        SingleFileDataset(f"{prefix}_00.btr", allow_pickle=True)[3]["frameid"]
        == 3
    )


def test_file_dataset_glob_concat(tmp_path):
    prefix = str(tmp_path / "run")
    n_per = [3, 2]
    for w, n in enumerate(n_per):
        with FileRecorder(FileRecorder.filename(prefix, w)) as rec:
            for i in range(n):
                rec.save(encode_message(_item(w * 10 + i)))
    ds = FileDataset(prefix)
    assert len(ds) == 5
    assert [m["frameid"] for m in ds] == [0, 1, 2, 10, 11]
    single = SingleFileDataset(
        FileRecorder.filename(prefix, 1), item_transform=lambda m: m["frameid"]
    )
    assert [single[i] for i in range(len(single))] == [10, 11]
    with pytest.raises(FileNotFoundError):
        FileDataset(str(tmp_path / "nope"))


def test_map_datasets_strip_recorded_lineage_stamps(tmp_path):
    """BJX120 regression: map-style replay returns items WITHOUT the
    recorded transport stamps (`_seq`/`_pub_wall`/...). A recording made
    off a live wire carries them, and collating them into a train batch
    is exactly the stamp-leak-into-jit bug class — the datasets strip
    like ReplayStream does, while the raw FileReader stays verbatim.
    The content stamp (`_scenario`) survives: it must re-account
    deterministically on replay."""
    prefix = str(tmp_path / "run")
    with FileRecorder(FileRecorder.filename(prefix, 0)) as rec:
        for i in range(3):
            m = _item(i)
            m["_seq"] = i
            m["_pub_wall"] = 1e9 + i
            m["_pub_mono"] = float(i)
            m["_scenario"] = {"sid": "a", "weight": 1.0}
            rec.save(encode_message(m))
    path = FileRecorder.filename(prefix, 0)
    raw = FileReader(path)[1]
    assert raw["_seq"] == 1  # the reader is the raw-access layer
    for ds in (SingleFileDataset(path), FileDataset(prefix)):
        item = ds[1]
        assert item["frameid"] == 1
        assert not {"_seq", "_pub_wall", "_pub_mono"} & set(item)
        assert item["_scenario"]["sid"] == "a"


# -- live stream ------------------------------------------------------------


def _publish_async(pub, items):
    """PUSH with no connected peer blocks, so tests publish off-thread."""
    t = threading.Thread(
        target=lambda: [pub.publish(**it) for it in items], daemon=True
    )
    t.start()
    return t


def test_remote_stream_max_items_and_transform_and_recording(tmp_path):
    pub = DataPublisherSocket(WILD, btid=1)
    prefix = str(tmp_path / "tee")
    stream = RemoteStream(
        [pub.addr],
        max_items=6,
        timeoutms=5000,
        item_transform=lambda m: m["frameid"] * 2,
        record_path_prefix=prefix,
    )
    t = _publish_async(pub, [_item(i) for i in range(6)])
    got = list(stream)
    t.join(timeout=10)
    assert got == [0, 2, 4, 6, 8, 10]
    # recording captured the raw (untransformed) messages
    reader = FileReader(FileRecorder.filename(prefix, 0))
    assert len(reader) == 6 and reader[0]["frameid"] == 0
    pub.close()


def test_remote_stream_worker_split():
    s = RemoteStream(["tcp://x"], max_items=10, worker_index=0, num_workers=4)
    assert s.worker_items() == 4  # 2 + remainder 2
    s = RemoteStream(["tcp://x"], max_items=10, worker_index=3, num_workers=4)
    assert s.worker_items() == 2
    s = RemoteStream(["tcp://x"], max_items=0)
    assert list(s) == []


# -- batching ---------------------------------------------------------------


def test_batch_assembler_flush_emits_partial_tail():
    schema = StreamSchema.infer(_item(0))
    asm = BatchAssembler(schema, batch_size=4)
    assert asm.flush() is None  # nothing pending
    for i in range(6):
        asm.add(_item(i))
    tail = asm.flush()
    assert tail["_partial"] is True
    np.testing.assert_array_equal(tail["frameid"], [4, 5])
    assert tail["image"].shape == (2, 4, 6, 4)
    assert [m["btid"] for m in tail["_meta"]] == [0, 0]
    assert asm.flush() is None  # one-shot


def test_host_ingest_emit_partial_final():
    """A finite stream's tail items surface as a _partial batch when
    opted in — and stay dropped (reference behavior) by default."""
    items = [_item(i) for i in range(6)]
    batches = list(HostIngest(items, batch_size=4, emit_partial_final=True))
    assert len(batches) == 2
    assert not batches[0].get("_partial")
    assert batches[1]["_partial"] and len(batches[1]["frameid"]) == 2
    got = sorted(int(v) for b in batches for v in b["frameid"])
    assert got == list(range(6))
    # default: tail silently dropped, exactly as before
    batches = list(HostIngest([_item(i) for i in range(6)], batch_size=4))
    assert len(batches) == 1 and len(batches[0]["frameid"]) == 4


def test_host_ingest_stop_returns_promptly_and_joins():
    """The stop() shutdown race: signalling then draining ONCE could
    swallow _DONE while the thread was still emitting, leaving join to
    burn its whole timeout. stop() must return promptly with the thread
    actually dead — even when the worker sits in a long recv (the
    request_stop path) or keeps producing into a full queue."""
    # blocked-in-recv case: 60s timeout, no producer traffic
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=60_000)
    ingest = HostIngest(stream, batch_size=4, prefetch=1).start()
    time.sleep(0.4)  # thread is inside the sliced poll
    t0 = time.monotonic()
    ingest.stop()
    assert time.monotonic() - t0 < 5.0
    assert not ingest._thread.is_alive()
    pub.close()

    # producing-into-full-queue case: infinite stream, consumer absent
    def forever():
        i = 0
        while True:
            yield _item(i)
            i += 1

    ingest = HostIngest(forever(), batch_size=2, prefetch=1).start()
    time.sleep(0.4)  # queue is full, thread parked in _emit
    t0 = time.monotonic()
    ingest.stop()
    assert time.monotonic() - t0 < 5.0
    assert not ingest._thread.is_alive()


def test_batch_assembler_packs_and_recycles():
    schema = StreamSchema.infer(_item(0))
    asm = BatchAssembler(schema, batch_size=3, num_buffers=2)
    batches = []
    for i in range(6):
        b = asm.add(_item(i))
        if b is not None:
            batches.append(b)
    assert len(batches) == 2
    np.testing.assert_array_equal(batches[0]["frameid"], [0, 1, 2])
    np.testing.assert_array_equal(batches[1]["frameid"], [3, 4, 5])
    assert batches[0]["image"].shape == (3, 4, 6, 4)
    assert [m["btid"] for m in batches[0]["_meta"]] == [0, 0, 0]
    # double buffering: batch 0's memory wasn't clobbered by batch 1
    assert batches[0]["image"] is not batches[1]["image"]


def test_host_ingest_streams_batches_and_propagates_timeout():
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=400, max_items=None)
    ingest = HostIngest(stream, batch_size=4, prefetch=2)
    t = _publish_async(pub, [_item(i) for i in range(8)])
    it = iter(ingest)
    b1 = next(it)
    b2 = next(it)
    assert b1["image"].shape == (4, 4, 6, 4)
    assert set(b1["frameid"]) | set(b2["frameid"]) == set(range(8))
    assert ingest.items_in == 8
    t.join(timeout=10)
    # producer goes silent -> the receive timeout surfaces in the consumer
    with pytest.raises(ReceiveTimeoutError):
        next(it)
    pub.close()


def test_host_ingest_schema_mismatch_fails_fast():
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=2)
    bad = _item(1)
    bad["image"] = np.zeros((9, 9, 4), np.uint8)
    t = _publish_async(pub, [_item(0), bad])
    with pytest.raises(SchemaError):
        list(ingest)
    t.join(timeout=10)
    pub.close()


# -- producer-side batching --------------------------------------------------


def _batched_item(start, b, h=4, w=6):
    return {
        "btid": 0,
        "_batched": True,
        "image": np.stack([np.full((h, w, 4), (start + i) % 255, np.uint8)
                           for i in range(b)]),
        "xy": np.stack([np.full((8, 2), float(start + i), np.float32)
                        for i in range(b)]),
        "frameid": np.arange(start, start + b, dtype=np.int64),
    }


def test_host_ingest_passthrough_of_producer_batches():
    """A (B, ...) message with B == batch_size becomes a batch with zero
    re-assembly; _meta carries the shared btid per item."""
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=4, prefetch=2)
    t = _publish_async(pub, [_batched_item(0, 4), _batched_item(4, 4)])
    it = iter(ingest)
    b1, b2 = next(it), next(it)
    assert b1["image"].shape == (4, 4, 6, 4)
    got = set(b1["frameid"]) | set(b2["frameid"])
    assert got == set(range(8))
    assert [m["btid"] for m in b1["_meta"]] == [0] * 4
    assert ingest.items_in == 8
    t.join(timeout=10)
    ingest.stop()
    pub.close()


def test_host_ingest_rebatches_mismatched_producer_batches():
    """Producer batch size 3 != consumer batch size 2: items are split and
    re-assembled, nothing lost."""
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=2, prefetch=3)
    t = _publish_async(pub, [_batched_item(0, 3), _batched_item(3, 3)])
    it = iter(ingest)
    frames = []
    for _ in range(3):
        b = next(it)
        assert b["image"].shape == (2, 4, 6, 4)
        frames.extend(b["frameid"].tolist())
    assert sorted(frames) == list(range(6))
    t.join(timeout=10)
    ingest.stop()
    pub.close()


def test_passthrough_dtype_mismatch_falls_back_to_split():
    """A producer batch with the right shapes but a wrong dtype can't
    take the zero-copy passthrough; the split path engages and per-item
    validation rejects the items loudly (fail fast, not a silent cast
    into the preallocated buffers)."""
    from blendjax.data.batcher import passthrough_batch

    schema = StreamSchema.infer(_item(0))
    good = _batched_item(0, 4)
    good.pop("_batched")
    assert passthrough_batch(good, schema, 4) is not None
    bad = dict(good)
    bad["xy"] = bad["xy"].astype(np.float64)
    assert passthrough_batch(bad, schema, 4) is None  # falls back to split

    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=4)
    wire_bad = _batched_item(0, 4)
    wire_bad["xy"] = wire_bad["xy"].astype(np.float64)
    t = _publish_async(pub, [_batched_item(4, 4), wire_bad])
    with pytest.raises(SchemaError, match="dtype"):
        list(ingest)
    t.join(timeout=10)
    pub.close()


def test_batched_views_with_scalar_sidecar_fields():
    """Scalar (and mismatched-lead) sidecars replicate into every split
    item instead of being sliced; the passthrough correctly refuses the
    message (a scalar field can't match a (B,)-shaped schema spec)."""
    from blendjax.data.batcher import batched_views, passthrough_batch

    item = _batched_item(0, 3)
    item.pop("_batched")
    item["frameid"] = 7  # shared scalar, not a per-item array
    item["palette"] = np.arange(5)  # lead dim 5 != 3: sidecar, replicated
    views = list(batched_views(item))
    assert len(views) == 3
    assert [v["frameid"] for v in views] == [7, 7, 7]
    for v in views:
        np.testing.assert_array_equal(v["palette"], np.arange(5))
        assert v["image"].shape == (4, 6, 4)
    schema = StreamSchema.infer(_item(0))
    assert passthrough_batch(item, schema, 3) is None

    # end to end: the split path re-batches, scalar broadcast to items
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=3)
    msg = _batched_item(0, 3)
    msg["frameid"] = 7
    t = _publish_async(pub, [msg])
    batch = next(iter(ingest))
    np.testing.assert_array_equal(batch["frameid"], [7, 7, 7])
    t.join(timeout=10)
    ingest.stop()
    pub.close()


def test_passthrough_meta_fans_out_per_item():
    """_meta from a producer batch: per-item arrays slice out one row
    per item, shared scalars replicate — each item's provenance stays
    item-shaped for downstream consumers."""
    from blendjax.data.batcher import passthrough_batch

    schema = StreamSchema(
        {
            "image": (( 4, 6, 4), np.uint8),
            "xy": ((8, 2), np.float32),
            "frameid": ((), np.int64),
        },
        meta_keys=("btid", "seq", "tag"),
    )
    item = _batched_item(0, 4)
    item.pop("_batched")
    item["seq"] = np.arange(100, 104)  # per-item: fans out one each
    item["tag"] = "runA"  # shared: replicated
    batch = passthrough_batch(item, schema, 4)
    assert [m["seq"] for m in batch["_meta"]] == [100, 101, 102, 103]
    assert [m["tag"] for m in batch["_meta"]] == ["runA"] * 4
    assert [m["btid"] for m in batch["_meta"]] == [0] * 4


def test_host_ingest_mixed_batched_and_single_producers():
    """Schema inferred from a batched message matches per-item messages, so
    a mixed fleet interleaves cleanly."""
    pub = DataPublisherSocket(WILD, btid=0)
    stream = RemoteStream([pub.addr], timeoutms=2000)
    ingest = HostIngest(stream, batch_size=4)
    msgs = [_batched_item(0, 4), _item(4), _item(5), _item(6), _item(7)]
    t = _publish_async(pub, msgs)
    it = iter(ingest)
    b1, b2 = next(it), next(it)
    assert sorted([*b1["frameid"], *b2["frameid"]]) == list(range(8))
    t.join(timeout=10)
    ingest.stop()
    pub.close()
