"""Bidirectional duplex optimization pieces: score-function updates,
param fan-out, and the full consumer<->producer round trip."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blendjax.launcher import PythonProducerLauncher  # noqa: E402
from blendjax.data import RemoteStream  # noqa: E402
from blendjax.train.score import GaussianSimParams, chunk_across  # noqa: E402
from blendjax.transport import PairChannel  # noqa: E402

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "densityopt",
    "supershape_producer.py",
)


def test_chunk_across():
    assert chunk_across([1, 2, 3, 4, 5], 2) == [[1, 2, 3], [4, 5]]
    assert chunk_across([1], 3) == [[1], [], []]


def test_gaussian_score_update_moves_toward_low_loss():
    sim = GaussianSimParams(mu=[5.0], log_sigma=[0.0], learning_rate=0.2)
    key = jax.random.key(0)
    # loss = |theta - 2| : minimum at 2, so mu must decrease from 5
    for _ in range(30):
        key, sub = jax.random.split(key)
        theta = np.asarray(sim.sample(sub, 16))
        losses = np.abs(theta[:, 0] - 2.0)
        sim.update(theta, losses)
    assert float(sim.mu[0]) < 4.0


def test_duplex_roundtrip_with_shape_ids():
    """Params sent over CTRL come back associated via shape_id on DATA
    (the pattern any learned-simulation loop must keep, SURVEY.md §3.3)."""
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA", "CTRL"],
        seed=0,
    ) as launcher:
        remotes = [
            PairChannel(a, bind=False) for a in launcher.addresses["CTRL"]
        ]
        sent = {}
        for i, (remote, ids) in enumerate(
            zip(remotes, chunk_across(list(range(6)), 2))
        ):
            for sid in ids:
                m = 3.0 + sid
                remote.send(
                    shape_params=np.array([m, 1, 1, 1], np.float32),
                    shape_id=sid,
                )
                sent[sid] = m
        stream = iter(
            RemoteStream(launcher.addresses["DATA"], timeoutms=30_000)
        )
        got = {}
        while len(got) < 6:
            item = next(stream)
            if item["shape_id"] in sent and item["shape_id"] not in got:
                got[item["shape_id"]] = item["image"].copy()
        assert set(got) == set(sent)
        # different params produce different renders
        assert (got[0] != got[5]).any()
        for r in remotes:
            r.close()
