"""The device ledger (blendjax.obs.devledger): HLO collective parsing,
graceful degradation of the compile-time extraction, the retrace audit,
the driver's cost-model MFU hand-off, the doctor's retrace-storm /
memory-bound arms, and the reporter/flight-bundle surfaces."""

import json
import os
import types

import numpy as np
import pytest

from blendjax.obs import diagnose
from blendjax.obs.devledger import (
    COLLECTIVE_KINDS,
    COLLECTIVE_METRICS,
    HBM_GAUGES,
    LEDGER_GAUGES,
    UNAVAILABLE,
    ExecutableLedger,
    RetraceAudit,
    batch_signature,
    default_peak_flops,
    ledger as global_ledger,
    measure_model_flops,
    parse_collectives,
)
from blendjax.utils.metrics import Metrics


# -- HLO collective parsing --------------------------------------------------


def test_parse_collectives_iota_groups_and_axis_attribution():
    hlo = (
        "%ar = f32[256]{0} all-reduce(%p0), "
        "replica_groups=[2,4]<=[8], to_apply=%add\n"
    )
    out = parse_collectives(hlo, mesh_axes={"data": 4, "model": 2})
    assert out["ops"] == 1
    assert out["per_kind"]["all-reduce"] == 256 * 4
    assert out["total_bytes"] == 1024
    # iota group size is the SECOND number: [2,4]<=[8] is 2 groups of 4,
    # which matches the size-4 "data" axis
    assert out["per_axis"] == {"data": 1024}


def test_parse_collectives_brace_groups_and_dtype_widths():
    hlo = (
        "%ag = bf16[8,16]{1,0} all-gather(%p0), "
        "replica_groups={{0,1},{2,3}}, dimensions={0}\n"
    )
    out = parse_collectives(hlo, mesh_axes={"x": 2, "y": 8})
    assert out["per_kind"]["all-gather"] == 8 * 16 * 2  # bf16 is 2 bytes
    assert out["per_axis"] == {"x": 256}


def test_parse_collectives_done_counted_once_on_start():
    hlo = (
        "%s = (f32[64]{0}, f32[64]{0}) all-reduce-start(%p1), "
        "replica_groups=[1,8]<=[8]\n"
        "%d = f32[64]{0} all-reduce-done(%s)\n"
    )
    out = parse_collectives(hlo)
    assert out["ops"] == 1  # the -done line adds nothing


def test_parse_collectives_unmatched_group_lands_under_unknown():
    hlo = (
        "%ar = f32[32]{0} all-reduce(%p0), "
        "replica_groups=[2,4]<=[8], to_apply=%add\n"
    )
    out = parse_collectives(hlo, mesh_axes={"data": 3})
    assert out["per_axis"] == {"unknown": 128}


def test_parse_collectives_every_kind_recognized():
    hlo = (
        "%a = f32[8]{0} all-reduce(%p0), replica_groups=[1,2]<=[2]\n"
        "%b = f32[8]{0} all-gather(%p0), replica_groups=[1,2]<=[2]\n"
        "%c = f32[8]{0} reduce-scatter(%p0), replica_groups=[1,2]<=[2]\n"
        "%d = f32[8]{0} collective-permute(%p0), "
        "source_target_pairs={{0,1}}\n"
        "%e = f32[8]{0} all-to-all(%p0), replica_groups=[1,2]<=[2]\n"
    )
    out = parse_collectives(hlo)
    assert out["ops"] == len(COLLECTIVE_KINDS)
    assert all(out["per_kind"][k] == 32 for k in COLLECTIVE_KINDS)
    assert out["total_bytes"] == 32 * 5


def test_parse_collectives_empty_hlo():
    out = parse_collectives("ENTRY %main { %p = f32[4]{0} parameter(0) }")
    assert out == {
        "total_bytes": 0, "ops": 0,
        "per_kind": {k: 0 for k in COLLECTIVE_KINDS}, "per_axis": {},
    }


# -- batch signatures --------------------------------------------------------


def test_batch_signature_sorted_mask_kept_underscores_scalars_dropped():
    arr = types.SimpleNamespace
    batch = {
        "image": arr(shape=(4, 8, 8, 4), dtype="uint8"),
        "_seq": arr(shape=(4,), dtype="int64"),
        "_mask": arr(shape=(4,), dtype="float32"),
        "scalar": arr(shape=(), dtype="float32"),
    }
    assert batch_signature(batch) == (
        ("_mask", (4,), "float32"),
        ("image", (4, 8, 8, 4), "uint8"),
    )


# -- compile-time extraction: good path and graceful degradation -------------


class _MemAnalysis:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 200
    temp_size_in_bytes = 300
    generated_code_size_in_bytes = 50
    alias_size_in_bytes = 100


class _GoodCompiled:
    def cost_analysis(self):
        return [{"flops": 1200.0, "bytes accessed": 3400.0}]

    def memory_analysis(self):
        return _MemAnalysis()

    def as_text(self):
        return (
            "%ar = f32[64]{0} all-reduce(%p0), "
            "replica_groups=[1,4]<=[4], to_apply=%add\n"
        )


class _BrokenCompiled:
    def cost_analysis(self):
        return None

    def memory_analysis(self):
        raise RuntimeError("backend has no memory analysis")

    def as_text(self):
        raise RuntimeError("no HLO text")


def test_register_extracts_and_publishes_gauges():
    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    sig = (("image", (8, 16, 16, 4), "uint8"), ("xy", (8, 8, 2), "float32"))
    entry = led.register("step", _GoodCompiled(), signature=sig,
                         mesh={"data": 4})
    assert entry["flops"] == 1200.0
    assert entry["bytes_accessed"] == 3400.0
    # donated/aliased buffers counted once in the peak
    assert entry["hbm_peak_bytes"] == 1000 + 200 + 300 + 50 - 100
    assert entry["batch_images"] == 8
    assert entry["collectives"]["per_axis"] == {"data": 256}
    g = reg.report()["gauges"]
    assert g["device.flops_per_step"] == 1200.0
    assert g["device.hbm_peak_bytes"] == 1450
    assert g["device.collective_bytes"] == 256
    assert g["device.collective.all_reduce_bytes"] == 256
    assert g["device.collective.all_gather_bytes"] == 0
    assert "device.ledger_failures" not in reg.report()["counters"]


def test_register_degrades_to_unavailable_and_never_raises():
    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    entry = led.register("broken", _BrokenCompiled())
    assert entry["flops"] == UNAVAILABLE
    assert entry["bytes_accessed"] == UNAVAILABLE
    assert entry["hbm_peak_bytes"] == UNAVAILABLE
    assert entry["temp_bytes"] == UNAVAILABLE
    assert entry["collectives"] == UNAVAILABLE
    rep = reg.report()
    assert rep["counters"]["device.ledger_failures"] == 3
    # unavailable fields stay out of the gauges entirely
    assert not any(k.startswith("device.") for k in rep["gauges"])
    # and the structured report still serializes
    json.dumps(led.report())


def test_register_empty_cost_analysis_degrades_only_that_field():
    class _EmptyCost(_GoodCompiled):
        def cost_analysis(self):
            return []

    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    entry = led.register("partial", _EmptyCost())
    assert entry["flops"] == UNAVAILABLE
    assert entry["hbm_peak_bytes"] == 1450  # memory half still lands
    assert reg.report()["counters"]["device.ledger_failures"] == 1
    assert reg.report()["gauges"]["device.hbm_peak_bytes"] == 1450
    assert "device.flops_per_step" not in reg.report()["gauges"]


def test_flops_per_image_prefers_matching_then_largest_lead():
    led = ExecutableLedger(registry=Metrics())

    class _Flops(_GoodCompiled):
        def __init__(self, flops):
            self._f = flops

        def cost_analysis(self):
            return [{"flops": self._f, "bytes accessed": 0.0}]

    led.register("a", _Flops(800.0),
                 signature=(("image", (4, 8, 8, 4), "uint8"),))
    led.register("b", _Flops(1600.0),
                 signature=(("image", (8, 8, 8, 4), "uint8"),))
    assert led.flops_per_image() == 1600.0 / 8
    assert led.flops_per_image(batch_images=4) == 800.0 / 4
    assert led.flops_per_image(batch_images=99) == 1600.0 / 8  # fallback


def test_catalog_tuples_cover_the_documented_family():
    # the BJX123 contract gate enumerates these module-level catalogs;
    # pin their shape so a rename keeps docs and code in one motion
    assert len(COLLECTIVE_METRICS) == len(COLLECTIVE_KINDS)
    assert all(m.startswith("device.collective.") for m in COLLECTIVE_METRICS)
    assert len(LEDGER_GAUGES) == 8 and len(HBM_GAUGES) == 4
    assert all(m.startswith("device.") for m in LEDGER_GAUGES + HBM_GAUGES)


# -- runtime HBM poll --------------------------------------------------------


def test_poll_memory_is_a_graceful_noop_on_cpu():
    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    assert led.poll_memory(reg) is None
    assert not any(k.startswith("device.hbm") for k in reg.report()["gauges"])
    assert led.report()["memory"] in (None, {"supported": False})


def test_default_peak_flops_unknown_backend_is_none():
    # tier-1 runs on JAX_PLATFORMS=cpu: no known-chip match, no guess
    assert default_peak_flops() is None


# -- retrace events and the audit --------------------------------------------


class _Flight:
    def __init__(self):
        self.dumps = []

    def dump(self, **kw):
        self.dumps.append(kw)


def test_note_retrace_counts_attributes_and_fires_flight_once():
    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    fl = _Flight()
    led.attach_flight(fl, threshold=2)
    sig = (("image", (6, 8, 8, 4), "uint8"),)
    led.note_retrace(sig)
    assert not fl.dumps
    led.note_retrace(sig)
    assert len(fl.dumps) == 1  # threshold crossed
    led.note_retrace(sig)
    assert len(fl.dumps) == 1  # one-shot
    assert reg.report()["counters"]["device.retraces"] == 3
    rep = led.report()["retraces"]
    assert rep["count"] == 3
    assert "(6, 8, 8, 4)" in rep["events"][0]["signature"]


def test_retrace_audit_counts_unbucketed_shape_exactly_once():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    reg = Metrics()
    led = ExecutableLedger(registry=reg)
    f = jax.jit(lambda x: x + 1)
    audit = RetraceAudit(f, warmup=1, ledger=led)
    assert audit.active
    x4 = jnp.zeros((4,))
    f(x4)
    assert audit.observe({"image": x4}) is False  # warm-up baseline
    f(x4)
    assert audit.observe({"image": x4}) is False  # cache hit
    x6 = jnp.zeros((6,))
    f(x6)
    assert audit.observe({"image": x6}) is True  # unbucketed: counted
    f(x6)
    assert audit.observe({"image": x6}) is False  # now cached: once only
    assert led.retrace_count == 1
    ev = led.report()["retraces"]["events"]
    assert "(6,)" in ev[0]["signature"]
    assert reg.report()["counters"]["device.retraces"] == 1


def test_retrace_audit_inactive_without_a_jit_cache():
    assert RetraceAudit.for_step(lambda x: x) is None


def test_retrace_audit_unwraps_aot_fallback_step():
    jax = pytest.importorskip("jax")

    wrapper = types.SimpleNamespace(_step=jax.jit(lambda x: x))
    assert RetraceAudit.for_step(wrapper) is not None


# -- the doctor's device arms ------------------------------------------------


def _report(spans=None, counters=None, gauges=None):
    return {
        "spans": {
            k: {"count": 10, "total_s": v} for k, v in (spans or {}).items()
        },
        "counters": counters or {},
        "gauges": gauges or {},
        "histograms": {},
    }


def test_doctor_retrace_storm():
    v = diagnose(_report(
        spans={"train.dispatch": 2.0},
        counters={"device.retraces": 3},
    ))
    assert v.kind == "retrace-storm"
    assert "device.retraces=3" in v.reason
    assert "pad" in v.advice or "bucket" in v.advice


def test_doctor_retraces_below_threshold_not_a_storm():
    v = diagnose(_report(
        spans={"train.dispatch": 2.0},
        counters={"device.retraces": 2},
    ))
    assert v.kind != "retrace-storm"


def test_doctor_memory_bound_temp_dominant_names_scratch():
    v = diagnose(_report(
        spans={"train.dispatch": 2.0},
        gauges={"device.hbm_headroom_frac": 0.05,
                "device.temp_bytes": 800.0,
                "device.hbm_peak_bytes": 1000.0},
    ))
    assert v.kind == "memory-bound"
    assert "temporaries" in v.reason


def test_doctor_memory_bound_resident_state_names_fsdp_lever():
    v = diagnose(_report(
        spans={"train.dispatch": 2.0},
        gauges={"device.hbm_headroom_frac": 0.03,
                "device.temp_bytes": 100.0,
                "device.hbm_peak_bytes": 1000.0},
    ))
    assert v.kind == "memory-bound"
    assert "resident state" in v.reason
    assert "fsdp" in v.advice


def test_doctor_healthy_headroom_not_memory_bound():
    v = diagnose(_report(
        spans={"train.dispatch": 2.0},
        gauges={"device.hbm_headroom_frac": 0.5},
    ))
    assert v.kind != "memory-bound"


# -- reporter and flight-bundle surfaces -------------------------------------


def test_reporter_jsonl_carries_device_block(tmp_path):
    from blendjax.obs import StatsReporter
    from blendjax.obs.lineage import FrameLineage

    reg = Metrics()
    reg.gauge("device.flops_per_step", 10.0)
    reg.count("device.retraces", 1)
    path = str(tmp_path / "stats.jsonl")
    rep = StatsReporter(interval_s=3600, registry=reg,
                        lineage=FrameLineage(), jsonl_path=path)
    rep.tick()
    rec = json.loads(open(path).read().strip())
    assert rec["device"]["device.flops_per_step"] == 10.0
    assert rec["device"]["device.retraces"] == 1


def test_flight_bundle_contains_device_ledger(tmp_path):
    from blendjax.obs.watchdog import FlightRecorder

    global_ledger.reset()
    try:
        global_ledger._entries.append({"name": "t", "flops": 1.0})
        global_ledger._retraces.append({
            "signature": "(('image', (6,), 'float32'),)",
            "count": 1, "cache_size": 2,
        })
        rec = FlightRecorder(str(tmp_path))
        bundle = rec.dump(reason="test", registry=Metrics())
        data = json.load(open(os.path.join(bundle, "device_ledger.json")))
        assert data["entries"][0]["name"] == "t"
        assert data["retraces"]["count"] == 1
        assert "(6,)" in data["retraces"]["events"][0]["signature"]
    finally:
        global_ledger.reset()


# -- driver wiring (cost-model MFU hand-off) ---------------------------------


def _small_batch(batch=4):
    return {
        "image": np.zeros((batch, 16, 16, 4), np.uint8),
        "xy": np.zeros((batch, 8, 2), np.float32),
    }


def test_driver_build_adopts_cost_model_flops():
    pytest.importorskip("jax")
    from blendjax.models import CubeRegressor
    from blendjax.train.driver import TrainDriver

    global_ledger.reset()
    try:
        drv = TrainDriver.build(
            CubeRegressor(features=(2,)), _small_batch(), aot=True,
            buckets=(2,), inflight=2, sync_every=0, peak_flops=1e12,
        )
        assert drv.stats["mfu_source"] == "cost-model"
        assert drv.flops_per_image and drv.flops_per_image > 0
        # adoption reads the full-batch (lead 4) entry exactly
        entries = [
            e for e in global_ledger.report()["entries"]
            if e["batch_images"] == 4 and isinstance(e["flops"], float)
        ]
        assert entries
        assert drv.flops_per_image == entries[-1]["flops"] / 4
    finally:
        global_ledger.reset()


def test_driver_hand_fed_flops_override_wins():
    pytest.importorskip("jax")
    from blendjax.models import CubeRegressor
    from blendjax.train.driver import TrainDriver

    global_ledger.reset()
    try:
        drv = TrainDriver.build(
            CubeRegressor(features=(2,)), _small_batch(), aot=True,
            buckets=(2,), inflight=2, sync_every=0,
            flops_per_image=123.0, peak_flops=1e12,
        )
        assert drv.stats["mfu_source"] == "hand-fed"
        assert drv.flops_per_image == 123.0
    finally:
        global_ledger.reset()


def test_measure_model_flops_memo_and_small_geometry():
    pytest.importorskip("jax")
    from blendjax.obs.devledger import _FLOPS_MEMO

    out = measure_model_flops(shape=(16, 16), batch=2)
    assert out["flops_per_image"] > 0
    assert ("CubeRegressor", (16, 16), 2, None) in _FLOPS_MEMO
    assert measure_model_flops(shape=(16, 16), batch=2) == out  # memo hit
