"""Async overlap driver + fused decode + bucket padding (PR 3):

- fused full-frame-palette decode+step trains identically to the
  unfused device_stage -> chunked-step pipeline (and dispatches zero
  standalone decode jits),
- mask-padded bucket batches score and backpropagate identically to
  their exact-shape forms (and keep the jit compile cache bounded),
- TrainDriver keeps dispatches in flight with completion tracking:
  host blocks happen only when the ring is genuinely full, and the
  overlap-working case blocks no more than ``inflight`` times per
  epoch.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import optax  # noqa: E402

from blendjax.data.batcher import bucket_sizes, pad_to_bucket  # noqa: E402
from blendjax.train import TrainDriver  # noqa: E402
from blendjax.utils.metrics import metrics as reg  # noqa: E402


# -- shape-bucketed partials -------------------------------------------------


def test_bucket_sizes_ladder():
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(6) == (1, 2, 4, 6)
    assert bucket_sizes(1) == (1,)


def test_pad_to_bucket_shapes_and_mask():
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.integers(0, 255, (5, 8, 8, 4), np.uint8),
        "xy": rng.random((5, 8, 2)).astype(np.float32),
        "palette": np.zeros((16, 4), np.uint8),  # non-lead sidecar
        "_meta": [{}] * 5,
        "_partial": True,
    }
    out = pad_to_bucket(batch, batch_size=8)
    assert out["image"].shape == (8, 8, 8, 4)
    assert out["xy"].shape == (8, 8, 2)
    assert out["palette"].shape == (16, 4)  # untouched
    assert "_partial" not in out
    assert out["_mask"].tolist() == [1, 1, 1, 1, 1, 0, 0, 0]
    assert len(out["_meta"]) == 5  # true-length provenance preserved
    np.testing.assert_array_equal(out["image"][:5], batch["image"])
    assert not out["image"][5:].any()  # zero fill


def test_masked_loss_and_grads_match_exact_shape():
    """The acceptance contract: a bucket-padded partial batch must
    produce the same loss AND the same updated params as its
    exact-shape form (mask-weighted mean, true-count denominator)."""
    from blendjax.models import CubeRegressor
    from blendjax.train import make_supervised_step, make_train_state

    rng = np.random.default_rng(3)
    imgs = rng.integers(0, 255, (5, 16, 16, 4), np.uint8)
    xys = (rng.random((5, 8, 2)) * 16).astype(np.float32)
    s0 = make_train_state(
        CubeRegressor(), imgs, optimizer=optax.sgd(0.01)
    )
    step = make_supervised_step(donate=False)

    s_exact, m_exact = step(s0, {"image": imgs, "xy": xys})
    padded = pad_to_bucket(
        {"image": imgs, "xy": xys, "_partial": True}, batch_size=8
    )
    s_pad, m_pad = step(s0, padded)

    np.testing.assert_allclose(
        float(m_exact["loss"]), float(m_pad["loss"]), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        s_exact.params, s_pad.params,
    )


def test_bucketed_partials_keep_jit_cache_bounded():
    """Distinct tail sizes all land in one masked bucket shape: the
    step compiles once for the full batch and once for the bucket —
    never per ragged tail (the recompile this PR eliminates)."""
    from blendjax.models import CubeRegressor
    from blendjax.train import make_supervised_step, make_train_state

    rng = np.random.default_rng(4)
    full = {
        "image": rng.integers(0, 255, (8, 16, 16, 4), np.uint8),
        "xy": (rng.random((8, 8, 2)) * 16).astype(np.float32),
    }
    s = make_train_state(
        CubeRegressor(), full["image"], optimizer=optax.sgd(0.01)
    )
    step = make_supervised_step(donate=False)
    s, _ = step(s, full)
    for n in (5, 6, 7):
        padded = pad_to_bucket(
            {
                "image": full["image"][:n],
                "xy": full["xy"][:n],
                "_partial": True,
            },
            batch_size=8,
        )
        s, _ = step(s, padded)
    cache_size = getattr(step, "_cache_size", None)
    if cache_size is not None:  # jax-version tolerant
        assert cache_size() == 2, cache_size()


def test_pipeline_pads_partial_final_batches():
    """emit_partial_final tails come out of the pipeline bucket-padded
    with a _mask (pad_partial defaults on); pad_partial=False restores
    the exact ragged tail."""
    from blendjax.data import StreamDataPipeline

    def items(n):
        for i in range(n):
            yield {
                "image": np.full((8, 8, 4), i, np.uint8),
                "xy": np.zeros((8, 2), np.float32),
            }

    with StreamDataPipeline(
        items(7), batch_size=4, emit_partial_final=True
    ) as pipe:
        batches = list(pipe)
    tail = batches[-1]
    assert np.asarray(tail["image"]).shape[0] == 4
    assert np.asarray(tail["_mask"]).tolist() == [1.0, 1.0, 1.0, 0.0]

    with StreamDataPipeline(
        items(7), batch_size=4, emit_partial_final=True,
        pad_partial=False,
    ) as pipe:
        batches = list(pipe)
    assert np.asarray(batches[-1]["image"]).shape[0] == 3
    assert batches[-1].get("_partial") is True


# -- fused full-frame palette decode ----------------------------------------


def _pal_messages(frames, xys, h, w):
    from blendjax.ops.tiles import (
        FRAMEPAL_SUFFIXES,
        FRAMESHAPE_SUFFIX,
        PALETTE_SUFFIX,
        palettize_frames,
    )

    for g in range(len(xys)):
        batch = frames[2 * g: 2 * g + 2]
        packed, pal, bits = palettize_frames(batch)
        yield {
            "_prebatched": True, "btid": 0,
            "image" + FRAMEPAL_SUFFIXES[bits]: packed,
            "image" + PALETTE_SUFFIX: pal,
            "image" + FRAMESHAPE_SUFFIX: np.array(
                [h, w, 4, bits], np.int32
            ),
            "xy": xys[g],
        }


def test_fused_pal_step_matches_decode_then_step():
    """emit_packed + make_fused_tile_step on a full-frame PALETTE
    stream trains bit-identically to the decode-then-chunked-step
    pipeline — and issues ZERO standalone decode.dispatch jits (the
    decode lives inside the train jit)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.models import CubeRegressor
    from blendjax.train import (
        make_chunked_supervised_step,
        make_fused_tile_step,
        make_train_state,
    )

    rng = np.random.default_rng(7)
    h, w = 16, 24
    colors = rng.integers(0, 255, (5, 4), np.uint8)
    frames = colors[rng.integers(0, 5, (8, h, w))]
    xys = (rng.random((4, 2, 8, 2)) * 16).astype(np.float32)

    s0 = make_train_state(
        CubeRegressor(), frames[:2], optimizer=optax.sgd(0.01)
    )

    with StreamDataPipeline(
        _pal_messages(frames, xys, h, w), batch_size=2, chunk=2
    ) as pipe:
        decoded = list(pipe)
    assert [np.asarray(b["image"]).shape for b in decoded] == [
        (2, 2, h, w, 4)
    ] * 2
    chunked = make_chunked_supervised_step(donate=False)
    s_ref, ref_losses = s0, []
    for b in decoded:
        s_ref, m = chunked(s_ref, {"image": b["image"], "xy": b["xy"]})
        ref_losses.extend(np.asarray(m["loss"]).tolist())

    reg.reset()
    with StreamDataPipeline(
        _pal_messages(frames, xys, h, w), batch_size=2, chunk=2,
        emit_packed=True,
    ) as pipe:
        packed_batches = list(pipe)
    assert all("_pal" in b and "_packed" in b for b in packed_batches)
    fused = make_fused_tile_step(donate=False)
    s_fused, fused_losses = s0, []
    for b in packed_batches:
        s_fused, m = fused(s_fused, b)
        fused_losses.extend(np.asarray(m["loss"]).tolist())
    assert "decode.dispatch" not in reg.spans()

    np.testing.assert_allclose(fused_losses, ref_losses, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-8
        ),
        s_ref.params, s_fused.params,
    )


def test_fused_pal_emit_packed_chunk1_groups_k1():
    """chunk=1 + emit_packed still routes pal batches through the
    packed form (K'=1 groups), so the fused path never needs a
    chunked pipeline to eliminate the decode dispatch."""
    from blendjax.data import StreamDataPipeline

    rng = np.random.default_rng(9)
    h, w = 16, 24
    colors = rng.integers(0, 255, (3, 4), np.uint8)
    frames = colors[rng.integers(0, 3, (8, h, w))]
    xys = (rng.random((4, 2, 8, 2)) * 16).astype(np.float32)
    with StreamDataPipeline(
        _pal_messages(frames, xys, h, w), batch_size=2, chunk=1,
        emit_packed=True,
    ) as pipe:
        batches = list(pipe)
    assert len(batches) == 4
    for b in batches:
        assert "_pal" in b
        assert np.asarray(b["_packed"]).shape[0] == 1  # K'=1


# -- TrainDriver -------------------------------------------------------------


class _FakeLoss:
    """Stand-in for a dispatched loss array with a controllable
    readiness flag (jax.block_until_ready passes non-array leaves
    through untouched, so blocking on one is a no-op)."""

    def __init__(self, ready: bool):
        self._ready = ready

    def is_ready(self) -> bool:
        return self._ready


def _fake_step(ready: bool):
    def step(state, batch):
        return state + 1, {"loss": _FakeLoss(ready)}

    return step


def test_driver_overlap_blocks_at_most_inflight_times():
    """The acceptance contract: with overlap working (dispatches
    complete before the ring refills), the driver performs no more
    than ``inflight`` genuine host blocks per epoch — here zero."""
    drv = TrainDriver(
        _fake_step(ready=True), state=0, inflight=4, sync_every=0
    )
    for _ in range(64):
        drv.submit({"x": np.zeros(1)})
    stats = drv.stats
    assert stats["dispatches"] == 64
    assert stats["host_blocks"] <= drv.inflight
    assert stats["inflight_hwm"] <= drv.inflight


def test_driver_blocks_only_when_ring_genuinely_full():
    """Never-completing dispatches: the driver must bound the ring by
    blocking on the oldest entry — once per submit past the window,
    never more (no per-step serialization)."""
    drv = TrainDriver(
        _fake_step(ready=False), state=0, inflight=4, sync_every=0
    )
    for _ in range(12):
        drv.submit({"x": np.zeros(1)})
    stats = drv.stats
    assert stats["inflight_hwm"] == 4
    assert stats["host_blocks"] == 12 - 4  # one per ring-full submit
    assert stats["dispatches"] == 12


def test_driver_sync_every_and_finish_collect_losses():
    from blendjax.models import CubeRegressor
    from blendjax.train import make_supervised_step, make_train_state

    rng = np.random.default_rng(11)
    batch = {
        "image": rng.integers(0, 255, (8, 16, 16, 4), np.uint8),
        "xy": (rng.random((8, 8, 2)) * 16).astype(np.float32),
    }
    s0 = make_train_state(
        CubeRegressor(), batch["image"], optimizer=optax.sgd(0.01)
    )
    step = make_supervised_step(donate=False)
    drv = TrainDriver(step, s0, inflight=3, sync_every=4)
    for _ in range(8):
        drv.submit(dict(batch))
    state, final = drv.finish()
    assert isinstance(final, float) and np.isfinite(final)
    # 2 periodic syncs + the final drain
    assert len(drv.losses) == 3
    assert int(state.step) == 8
    # drain is idempotent once the ring is empty
    assert drv.drain() == final


def test_driver_pads_unmasked_partials():
    """A `_partial` batch that reaches the driver unmasked (pipeline
    configured with pad_partial=False, or hand-fed) is bucket-padded
    defensively, so it cannot recompile the step mid-run."""
    seen_shapes = []

    def step(state, batch):
        seen_shapes.append(batch["image"].shape)
        assert "_mask" in batch
        return state, {"loss": _FakeLoss(True)}

    drv = TrainDriver(step, state=0, inflight=2, sync_every=0)
    rng = np.random.default_rng(1)
    drv.submit({
        "image": rng.integers(0, 255, (5, 8, 8, 4), np.uint8),
        "xy": np.zeros((5, 8, 2), np.float32),
        "_partial": True,
    })
    assert seen_shapes == [(8, 8, 8, 4)]


def test_driver_run_drives_fused_pipeline_one_dispatch_per_step():
    """End to end: pipeline(emit_packed) -> fused step -> driver. The
    fused training path issues exactly ONE device dispatch per driver
    step and zero standalone decode dispatches."""
    from blendjax.data import StreamDataPipeline
    from blendjax.models import CubeRegressor
    from blendjax.train import make_fused_tile_step, make_train_state

    rng = np.random.default_rng(21)
    h, w = 16, 24
    colors = rng.integers(0, 255, (5, 4), np.uint8)
    frames = colors[rng.integers(0, 5, (8, h, w))]
    xys = (rng.random((4, 2, 8, 2)) * 16).astype(np.float32)
    s0 = make_train_state(
        CubeRegressor(), frames[:2], optimizer=optax.sgd(0.01)
    )
    reg.reset()
    step = make_fused_tile_step(donate=False)
    drv = TrainDriver(step, s0, inflight=2, sync_every=0)
    with StreamDataPipeline(
        _pal_messages(frames, xys, h, w), batch_size=2, chunk=2,
        emit_packed=True,
    ) as pipe:
        state, final = drv.run(pipe)
    assert drv.stats["steps"] == 2  # 4 batches in 2 chunk groups
    spans = reg.spans()
    assert spans["train.dispatch"]["count"] == drv.stats["dispatches"]
    assert "decode.dispatch" not in spans
    assert isinstance(final, float) and np.isfinite(final)


def test_driver_device_timeline_and_mfu_land_in_report():
    """Acceptance: a live driver run populates train.step_device_ms
    percentiles and (given flops_per_image + peak_flops) a train.mfu
    gauge in Metrics.report() — MFU as an always-on run metric, not a
    bench artifact."""
    from blendjax.models import CubeRegressor
    from blendjax.train import make_supervised_step, make_train_state

    rng = np.random.default_rng(13)
    batch = {
        "image": rng.integers(0, 255, (8, 16, 16, 4), np.uint8),
        "xy": (rng.random((8, 8, 2)) * 16).astype(np.float32),
    }
    s0 = make_train_state(
        CubeRegressor(), batch["image"], optimizer=optax.sgd(0.01)
    )
    reg.reset()
    drv = TrainDriver(
        make_supervised_step(donate=False), s0, inflight=2,
        sync_every=0, flops_per_image=1e9, peak_flops=197e12,
    )
    for _ in range(6):
        drv.submit(dict(batch))
    drv.finish()
    report = reg.report()
    h = report["histograms"]["train.step_device_ms"]
    assert h["count"] == 6  # every ring entry retired exactly once
    for q in ("p50", "p95", "p99"):
        assert h[q] >= 0, h
    assert drv.stats["images_retired"] == 6 * 8
    # whole-run MFU published at the drain barrier (short runs would
    # otherwise end inside the 1s gauge window)
    assert report["gauges"]["train.mfu"] > 0
    # without the flops hints the gauge is absent, the histogram stays
    reg.reset()
    drv2 = TrainDriver(
        make_supervised_step(donate=False), s0, inflight=2, sync_every=0
    )
    drv2.submit(dict(batch))
    drv2.finish()
    report = reg.report()
    assert "train.mfu" not in report["gauges"]
    assert report["histograms"]["train.step_device_ms"]["count"] == 1


def test_driver_place_mode_matches_feeder_path():
    """Lever 3 (placement folded into the dispatch): a pipeline in
    place_in_driver mode yields HOST batches, the driver commits the
    grouped device_put at submit, and the trained result is identical
    to the feeder-staged path — with the `feed.place` span now counted
    per submit and zero standalone decode dispatches."""
    import jax

    from blendjax.data import StreamDataPipeline
    from blendjax.models.cnn import CubeRegressor
    from blendjax.train.steps import make_fused_tile_step, make_train_state
    from blendjax.transport.wire import decode_message, encode_message

    B, H, W = 4, 32, 32
    frames = []
    for i in range(6):
        img = np.zeros((B, H, W, 4), np.uint8)
        img[:, 4 + i:14 + i, 6:22] = (i % 3) + 1
        xy = np.full((B, 8, 2), float(i % 9), np.float32)
        frames.append(encode_message(
            {"btid": 0, "_prebatched": True, "image": img, "xy": xy},
            compress_rle=True, rle_cap=128, compress_min_bytes=512,
        ))

    def run(place_in_driver):
        msgs = [
            decode_message(f, defer_rle=place_in_driver) for f in frames
        ]
        pipe = StreamDataPipeline(
            iter(msgs), batch_size=B, emit_packed=True,
            place_in_driver=place_in_driver,
        )
        model = CubeRegressor()
        state = make_train_state(
            model, np.zeros((B, H, W, 4), np.uint8),
            rng=jax.random.key(0),
        )
        drv = TrainDriver(
            make_fused_tile_step(), state, inflight=2, sync_every=0,
            place=pipe.feeder.place if place_in_driver else None,
        )
        with pipe:
            for b in pipe:
                drv.submit(b)
        _, loss = drv.finish()
        return drv, float(loss)

    reg.reset()
    drv_a, loss_a = run(True)
    report = reg.report()
    assert report["spans"]["feed.place"]["count"] == drv_a.steps
    assert "decode.dispatch" not in report["spans"]
    drv_b, loss_b = run(False)
    assert drv_a.steps == drv_b.steps == 6
    assert loss_a == loss_b
