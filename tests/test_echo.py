"""Data echoing (PR 5): device-resident sample reservoir + on-device
re-augmentation for producer-bound pipelines.

- reservoir ring semantics are deterministic under jit (insert order,
  wraparound, gather) and the donated insert never reallocates the
  device buffers,
- the echo budget is enforced exactly: no sample is ever drawn more
  than ``max_echo_factor`` times, ``min_fresh_fraction`` holds per
  batch, and ``echo.fresh + echo.echoed == steps * batch`` exactly,
- echoed draws decorrelate via the fused augmentation chain while
  spatial labels transform consistently with their images,
- the step loop never blocks while echo budget remains, sustains a
  step rate >= 4x the producer frame rate at ``max_echo_factor=8``,
  and composes with ``TrainDriver`` at exactly one dispatch per step,
- warm-start pre-fills the reservoir from a recording,
- the stall doctor reports the echo-mitigated / echo-saturated arms.
"""

import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import optax  # noqa: E402

from blendjax.data.echo import (  # noqa: E402
    EchoingPipeline,
    SampleReservoir,
)
from blendjax.obs import diagnose  # noqa: E402
from blendjax.utils.metrics import metrics as reg  # noqa: E402

B, H, W = 4, 8, 8


def _batch(i: int, b: int = B) -> dict:
    rng = np.random.default_rng(100 + i)
    return {
        "image": rng.integers(0, 255, (b, H, W, 4), np.uint8),
        "xy": (rng.random((b, 8, 2)) * H).astype(np.float32),
    }


def _batches(n: int, delay: float = 0.0, b: int = B):
    for i in range(n):
        if delay:
            time.sleep(delay)
        yield _batch(i, b)


# -- SampleReservoir ----------------------------------------------------------


def test_reservoir_ring_insert_gather_deterministic():
    res = SampleReservoir(capacity=8, augment=None)
    rows = [_batch(i) for i in range(3)]  # 12 samples into 8 slots
    slots = [res.insert(r) for r in rows]
    assert slots[0].tolist() == [0, 1, 2, 3]
    assert slots[1].tolist() == [4, 5, 6, 7]
    assert slots[2].tolist() == [0, 1, 2, 3]  # wrapped
    assert res.size == 8 and res.inserts == 12
    got = res.gather(np.arange(8))
    # slots 0-3 hold batch 2 (overwrote batch 0), 4-7 hold batch 1
    np.testing.assert_array_equal(
        np.asarray(got["image"][:4]), rows[2]["image"]
    )
    np.testing.assert_array_equal(
        np.asarray(got["image"][4:]), rows[1]["image"]
    )
    np.testing.assert_array_equal(np.asarray(got["xy"][:4]), rows[2]["xy"])
    # gather is a pure read: repeated gathers agree
    again = res.gather(np.arange(8))
    np.testing.assert_array_equal(
        np.asarray(got["image"]), np.asarray(again["image"])
    )


def test_reservoir_donated_insert_keeps_buffers_stable():
    """The ring is preallocated once and updated in place (donated
    scatter): the device buffer pointer never changes across inserts —
    no per-step reallocation of a potentially multi-GB reservoir."""
    res = SampleReservoir(capacity=16, augment=None)
    ptrs = set()
    for i in range(6):
        res.insert(_batch(i))
        ptrs.add(res._buffers["image"].unsafe_buffer_pointer())
    assert len(ptrs) == 1, ptrs


def test_reservoir_validates_structure_and_trims_oversize():
    res = SampleReservoir(capacity=4, augment=None)
    res.insert(_batch(0))
    with pytest.raises(ValueError, match="fields"):
        res.insert({"image": _batch(1)["image"]})
    with pytest.raises(ValueError, match="reservoir holds"):
        res.insert({
            "image": np.zeros((4, H, W, 3), np.uint8),
            "xy": np.zeros((4, 8, 2), np.float32),
        })
    # an oversized batch keeps only its newest `capacity` rows
    big = {
        "image": np.arange(6 * H * W * 4, dtype=np.uint8).reshape(
            6, H, W, 4
        ),
        "xy": np.tile(
            np.arange(6, dtype=np.float32)[:, None, None], (1, 8, 2)
        ),
    }
    slots = res.insert(big)
    assert len(slots) == 4
    got = res.gather(np.sort(slots))
    assert sorted(np.asarray(got["xy"])[:, 0, 0].tolist()) == [2, 3, 4, 5]


def test_sample_augment_decorrelates_and_replays_deterministically():
    from blendjax.data.echo import default_echo_augment

    # the photometric chain EchoingPipeline installs by default
    res = SampleReservoir(
        capacity=4, augment=default_echo_augment(), rng=7
    )
    res.insert(_batch(0))
    a = res.sample(np.array([1, 1, 2, 2]))
    b = res.sample(np.array([1, 1, 2, 2]))
    # two draws of the SAME slots differ (per-draw key fold) ...
    assert not np.array_equal(np.asarray(a["image"]), np.asarray(b["image"]))
    # ... while the labels stay untouched by the photometric default
    np.testing.assert_array_equal(np.asarray(a["xy"]), np.asarray(b["xy"]))
    # and the whole sequence replays exactly for the same rng seed
    res2 = SampleReservoir(
        capacity=4, augment=default_echo_augment(), rng=7
    )
    res2.insert(_batch(0))
    a2 = res2.sample(np.array([1, 1, 2, 2]))
    b2 = res2.sample(np.array([1, 1, 2, 2]))
    np.testing.assert_array_equal(
        np.asarray(a["image"]), np.asarray(a2["image"])
    )
    np.testing.assert_array_equal(
        np.asarray(b["image"]), np.asarray(b2["image"])
    )


def test_paired_batch_augment_keeps_points_consistent():
    """Geometric echo augmentation must transform spatial labels WITH
    the image: a point marking a bright pixel keeps marking it through
    flip + crop."""
    import functools

    from blendjax.ops.augment import (
        make_batch_augment,
        random_crop_with_points,
        random_flip_with_points,
    )

    rng = np.random.default_rng(3)
    images = np.zeros((B, 16, 16, 4), np.uint8)
    pts = np.zeros((B, 1, 2), np.float32)
    for i in range(B):
        x, y = rng.integers(4, 12, 2)
        images[i, y, x] = 255
        pts[i, 0] = (x, y)
    aug = make_batch_augment(
        random_flip_with_points,
        functools.partial(random_crop_with_points, pad=2),
        points_key="xy",
    )
    out = jax.jit(aug)(jax.random.key(0), {"image": images, "xy": pts})
    oi = np.asarray(out["image"])
    op = np.asarray(out["xy"])
    moved = 0
    for i in range(B):
        x, y = np.round(op[i, 0]).astype(int)
        if not (0 <= x < 16 and 0 <= y < 16):
            continue  # crop pushed the point off-frame: nothing to check
        assert oi[i, y, x, 0] == 255, (i, x, y)
        if (x, y) != tuple(np.round(pts[i, 0]).astype(int)):
            moved += 1
    # at least one sample actually transformed (key 0 flips ~half)
    assert moved >= 1


def test_batch_augment_requires_points_key_for_paired_ops():
    from blendjax.ops.augment import (
        make_batch_augment,
        random_flip_with_points,
    )

    with pytest.raises(ValueError, match="points_key"):
        make_batch_augment(random_flip_with_points)
    # a configured points_key whose field is missing from the batch
    # fails AT the misconfiguration, not as an opaque jit-trace error
    aug = make_batch_augment(random_flip_with_points, points_key="xy")
    with pytest.raises(KeyError, match="xy"):
        aug(jax.random.key(0), {"image": np.zeros((2, 8, 8, 4), np.uint8)})


def test_observe_many_matches_per_sample_observes():
    from blendjax.utils.metrics import Metrics

    a, b = Metrics(), Metrics()
    vals = np.random.default_rng(0).random(64) * 10
    for v in vals:
        a.observe("x", v)
    b.observe_many("x", vals)
    assert a.histograms()["x"] == b.histograms()["x"]


# -- EchoingPipeline: budget + accounting -------------------------------------


def test_echo_budget_exact_accounting_and_4x_rate():
    """The acceptance contract: with a rate-limited producer and
    ``max_echo_factor=8``, the pipeline emits steps at >= 4x the
    producer frame rate (here exactly 8x: every sample is drawn
    exactly its full budget), ``echo.fresh + echo.echoed ==
    steps * batch`` EXACTLY, and no sample exceeds the cap."""
    reg.reset()
    frames = 6 * B  # 24 samples, all resident (capacity 32: no eviction)
    with EchoingPipeline(
        _batches(6, delay=0.02), capacity=32, max_echo_factor=8,
        augment=None,
    ) as pipe:
        steps = sum(1 for _ in pipe)
    st = pipe.stats
    assert st["inserted"] == frames
    assert st["steps"] == steps
    # exact accounting, at any interleaving of drain vs draw
    assert st["fresh"] + st["echoed"] == steps * B
    counters = reg.report()["counters"]
    assert counters["echo.fresh"] == st["fresh"]
    assert counters["echo.echoed"] == st["echoed"]
    assert counters["echo.fresh"] + counters["echo.echoed"] == steps * B
    # every inserted sample drawn exactly its full budget -> 8x rate
    assert steps * B == frames * 8
    assert (pipe._use[pipe._filled] <= 8).all()
    assert st["fresh"] == frames  # each sample fresh exactly once
    assert st["unique_fraction"] == round(frames / (steps * B), 4)


def test_min_fresh_fraction_honored_per_batch():
    with EchoingPipeline(
        _batches(10), capacity=64, max_echo_factor=4,
        min_fresh_fraction=0.5, augment=None,
    ) as pipe:
        it = iter(pipe)
        prev = 0
        for batch in it:
            delta = pipe.fresh - prev
            prev = pipe.fresh
            # the floor holds on every live batch; only the post-stream
            # drain (inner done, fresh exhausted) may relax it
            if not (pipe._inner_done and delta < 2):
                assert delta >= 2, delta
    assert pipe.fresh + pipe.echoed == pipe.steps * B
    assert (pipe._use[pipe._filled] <= 4).all()


def test_steps_do_not_block_while_echo_budget_remains():
    """With one batch resident and budget left, draws proceed without a
    single fresh frame arriving — the producer is released only after
    the budget is spent, and only then does the loop wait."""
    release = threading.Event()

    def source():
        yield _batch(0)
        release.wait(timeout=10)
        yield _batch(1)

    reg.reset()
    with EchoingPipeline(
        source(), capacity=8, max_echo_factor=8, augment=None,
    ) as pipe:
        it = iter(pipe)
        for _ in range(8):  # 4 samples x budget 8 = 8 draws of B=4
            next(it)
        assert pipe.stats["inserted"] == B  # never needed batch 1
        assert pipe.stats["saturated_waits"] == 0
        release.set()
        next(it)  # budget spent: this draw needed fresh frames
        assert pipe.stats["inserted"] == 2 * B
    assert pipe.stats["saturated_waits"] >= 1
    assert reg.report()["counters"]["echo.saturated_waits"] >= 1


def test_stop_unblocks_a_saturated_draw_loop():
    """stop() from another thread must terminate a consumer parked in
    the saturated wait: the drain thread skips its _DONE sentinel once
    stopped, so the draw loop has to watch the stop flag itself."""

    def source():
        yield _batch(0)
        threading.Event().wait(10)  # a producer that never ends

    pipe = EchoingPipeline(
        source(), capacity=8, max_echo_factor=1, augment=None,
    )
    it = iter(pipe)
    next(it)  # 4 samples x budget 1 = exactly one draw; now saturated
    tail = []
    t = threading.Thread(
        target=lambda: tail.append(sum(1 for _ in it)), daemon=True
    )
    t.start()
    time.sleep(0.3)  # let the consumer park in the saturated wait
    pipe.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    assert tail == [0]


def test_inner_pipeline_error_surfaces_promptly():
    """A crashed stream must raise within the next draw or two — not
    after the whole remaining echo budget (capacity * factor samples)
    has been drained with the fresh floor silently relaxed."""

    def source():
        yield _batch(0)
        raise RuntimeError("socket died")

    with EchoingPipeline(
        source(), capacity=64, max_echo_factor=1000,
        min_fresh_fraction=0.5, augment=None,
    ) as pipe:
        it = iter(pipe)
        drawn = 0
        with pytest.raises(RuntimeError, match="socket died"):
            for _ in it:
                drawn += 1
    # far below the 4 * 1000 / 4 = 1000 draws the budget would allow
    assert drawn <= 3, drawn


def test_partial_masked_tails_are_not_echoed():
    reg.reset()

    def source():
        yield _batch(0)
        yield {**_batch(1, b=2), "_mask": np.array([1, 0], np.float32)}

    with EchoingPipeline(
        source(), capacity=8, max_echo_factor=2, augment=None,
    ) as pipe:
        sum(1 for _ in pipe)
    assert pipe.stats["inserted"] == B  # the masked tail was skipped
    assert reg.report()["counters"]["echo.skipped_partial"] == 1


# -- integration: StreamDataPipeline + TrainDriver ---------------------------


def _items(n: int, delay: float = 0.0):
    for i in range(n):
        if delay:
            time.sleep(delay)
        rng = np.random.default_rng(i)
        yield {
            "image": rng.integers(0, 255, (H, W, 4), np.uint8),
            "xy": (rng.random((8, 2)) * H).astype(np.float32),
        }


def test_echo_over_stream_pipeline_driver_one_dispatch_per_step():
    """End to end: StreamDataPipeline -> EchoingPipeline ->
    TrainDriver. Exactly ONE train dispatch per step
    (dispatch_per_step == 1.0), zero standalone decode dispatches,
    exact echo accounting, and the step count outruns the frame count
    by the full echo factor."""
    from blendjax.data import StreamDataPipeline
    from blendjax.models import CubeRegressor
    from blendjax.train import (
        TrainDriver,
        make_supervised_step,
        make_train_state,
    )

    reg.reset()
    s0 = make_train_state(
        CubeRegressor(), np.zeros((B, H, W, 4), np.uint8),
        optimizer=optax.sgd(0.01),
    )
    step = make_supervised_step(donate=False)
    drv = TrainDriver(step, s0, inflight=2, sync_every=0)
    inner = StreamDataPipeline(_items(4 * B), batch_size=B)
    with EchoingPipeline(
        inner, capacity=32, max_echo_factor=8,
    ) as pipe:
        state, final = drv.run(pipe)
    st = pipe.stats
    assert st["inserted"] == 4 * B
    assert drv.stats["steps"] == st["steps"] == 4 * 8
    assert st["fresh"] + st["echoed"] == st["steps"] * B
    spans = reg.spans()
    assert spans["train.dispatch"]["count"] == drv.stats["steps"]
    assert "decode.dispatch" not in spans
    dispatch_per_step = (
        spans["train.dispatch"]["count"]
        + spans.get("decode.dispatch", {}).get("count", 0)
    ) / drv.stats["steps"]
    assert dispatch_per_step == 1.0
    assert "echo.insert" in spans and "echo.sample" in spans
    assert isinstance(final, float) and np.isfinite(final)
    assert int(state.step) == drv.stats["steps"]
    # reservoir age histogram fed through the exact Histogram
    hists = reg.histograms()
    assert hists["echo.sample_age_s"]["count"] == st["steps"] * B


def test_echoing_pipeline_rejects_packed_and_chunked_pipelines():
    from blendjax.data import StreamDataPipeline

    chunked = StreamDataPipeline(_items(4), batch_size=2, chunk=2)
    with pytest.raises(ValueError, match="chunk=1"):
        EchoingPipeline(chunked)
    packed = StreamDataPipeline(_items(4), batch_size=2, emit_packed=True)
    with pytest.raises(ValueError, match="chunk=1"):
        EchoingPipeline(packed)


# -- warm start ---------------------------------------------------------------


def test_warm_start_prefills_reservoir_from_recording(tmp_path):
    from blendjax.data import FileRecorder
    from blendjax.transport.wire import encode_message

    path = str(tmp_path / "warm.bjr")
    with FileRecorder(path) as rec:
        for item in _items(2 * B):
            rec.save(encode_message(item))

    blocked = threading.Event()

    def live_source():
        blocked.wait(timeout=10)
        return
        yield  # pragma: no cover - empty live stream

    with EchoingPipeline(
        live_source(), capacity=8, max_echo_factor=2, batch_size=B,
        augment=None, warm_start=path,
    ) as pipe:
        it = iter(pipe)
        first = next(it)  # step 0: no live frame ever arrived
        assert np.asarray(first["image"]).shape == (B, H, W, 4)
        assert pipe.stats["inserted"] == 2 * B
        assert pipe.stats["reservoir_fill"] == 8
        blocked.set()
        rest = sum(1 for _ in it)
    # warm samples carry the full echo budget: 8 resident x factor 2
    assert (1 + rest) * B == 8 * 2
    assert pipe.fresh + pipe.echoed == pipe.steps * B


def test_warm_start_requires_batch_size():
    with pytest.raises(ValueError, match="batch_size"):
        iter(EchoingPipeline(iter(()), warm_start="nope.bjr"))


# -- doctor: echo arms --------------------------------------------------------


def _report(spans=None, counters=None, gauges=None):
    return {
        "spans": {
            k: {"count": 10, "total_s": v} for k, v in (spans or {}).items()
        },
        "counters": counters or {},
        "gauges": gauges or {},
        "histograms": {},
    }


def test_doctor_producer_bound_echo_mitigated():
    v = diagnose(_report(
        spans={"ingest.queue_wait": 6.0, "train.dispatch": 1.0},
        counters={"echo.fresh": 100, "echo.echoed": 700},
    ))
    assert v.kind == "producer-bound"
    assert "echo-mitigated" in v.reason
    assert "8.0x" in v.reason or "8.0" in v.reason
    assert "fresh-data diversity" in v.advice


def test_doctor_echo_saturated_on_budget_exhaustion():
    v = diagnose(_report(
        spans={"ingest.queue_wait": 6.0, "train.dispatch": 1.0},
        counters={"echo.fresh": 100, "echo.echoed": 700,
                  "echo.saturated_waits": 5},
    ))
    assert v.kind == "echo-saturated"
    assert "raise producer" in v.advice
    # the echoing loop's own starvation span is sufficient evidence
    # even when the inner consumer's queue_wait share is small
    v2 = diagnose(_report(
        spans={"echo.wait_fresh": 6.0, "train.dispatch": 1.0},
        counters={"echo.fresh": 10, "echo.echoed": 70},
    ))
    assert v2.kind == "echo-saturated"


def test_doctor_plain_producer_bound_unchanged_without_echo():
    v = diagnose(_report(
        spans={"ingest.queue_wait": 6.0, "train.dispatch": 1.0},
    ))
    assert v.kind == "producer-bound"
    assert "echo-mitigated" not in v.reason
    # ... and now points at the echo lever
    assert "EchoingPipeline" in v.advice
