"""Echo-fused train step (PR 9): gather + re-augmentation + loss +
donated update in ONE jit.

- f32 loss equality: the fused step trains EXACTLY the same math as
  the two-dispatch path (reservoir ``sample`` then supervised step) on
  the same draw sequence, augmentation included,
- exact echo accounting is preserved in ``emit_draws`` token mode,
- exactly one device dispatch per driver step, single-chip AND on the
  8-device mesh (no standalone ``echo.sample``/``decode.dispatch``),
- the reservoir ring's buffer pointers stay stable under the fused
  step's donation (the ring is read, never donated or copied), and the
  donated state reuses its buffers in place
  (:mod:`blendjax.testing.donation`).
"""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from blendjax.data.echo import (  # noqa: E402
    EchoingPipeline,
    SampleReservoir,
    default_echo_augment,
)
from blendjax.models import CubeRegressor  # noqa: E402
from blendjax.testing.donation import DonationAudit  # noqa: E402
from blendjax.train import (  # noqa: E402
    TrainDriver,
    make_echo_fused_step,
    make_supervised_step,
    make_train_state,
)
from blendjax.utils.metrics import metrics as reg  # noqa: E402

B, H, W = 4, 8, 8


def _batch(i: int, b: int = B) -> dict:
    rng = np.random.default_rng(100 + i)
    return {
        "image": rng.integers(0, 255, (b, H, W, 4), np.uint8),
        "xy": (rng.random((b, 8, 2)) * H).astype(np.float32),
    }


def _batches(n: int, delay: float = 0.0):
    for i in range(n):
        if delay:
            time.sleep(delay)
        yield _batch(i)


def _f32_state(rng_seed: int = 0):
    return make_train_state(
        CubeRegressor(dtype=jnp.float32),
        np.zeros((B, H, W, 4), np.uint8),
        optimizer=optax.sgd(0.01),
        rng=jax.random.key(rng_seed),
    )


def _filled_reservoir(augment, rng=7, capacity=8, n=2):
    res = SampleReservoir(capacity=capacity, augment=augment, rng=rng)
    for i in range(n):
        res.insert(_batch(i))
    return res


# -- f32 equality: fused vs sample+step ---------------------------------------


@pytest.mark.parametrize("augment", [None, "default"])
def test_fused_loss_equals_sample_plus_step_f32(augment):
    """The acceptance pin: on the same draw sequence (same slots, same
    draw counters, same augmentation keys) the fused one-dispatch step
    and the two-dispatch sample-then-step path produce equal f32
    losses and equal updated params."""
    aug = default_echo_augment() if augment == "default" else None
    draws = [
        np.array([0, 1, 2, 3]),
        np.array([4, 5, 0, 1]),  # re-draws decorrelate via the counter
        np.array([2, 2, 6, 7]),
    ]

    # two-dispatch reference: jitted gather+augment, then the plain
    # supervised step
    res_a = _filled_reservoir(aug)
    state_a = _f32_state()
    step_a = make_supervised_step(donate=False, precision="f32")
    losses_a = []
    for idx in draws:
        batch = res_a.sample(idx)
        state_a, m = step_a(state_a, batch)
        losses_a.append(float(np.asarray(m["loss"])))

    # fused: the SAME draw bodies trace inside the train jit
    res_b = _filled_reservoir(aug)
    state_b = _f32_state()
    step_b = make_echo_fused_step(
        reservoir_draw=res_b.draw, donate=False, precision="f32"
    )
    losses_b = []
    for idx in draws:
        state_b, m = step_b(state_b, res_b.draw_token(idx))
        losses_b.append(float(np.asarray(m["loss"])))

    np.testing.assert_allclose(losses_a, losses_b, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6
        ),
        state_a.params, state_b.params,
    )


def test_draw_token_and_sample_share_one_counter_sequence():
    """Token draws advance the SAME counter as eager draws, so a mixed
    run keeps one deterministic augmentation sequence."""
    res = _filled_reservoir(default_echo_augment())
    tok0 = res.draw_token(np.arange(4))
    assert int(tok0["_echo_counter"]) == 0
    res.sample(np.arange(4))  # counter 1
    tok2 = res.draw_token(np.arange(4))
    assert int(tok2["_echo_counter"]) == 2
    # and the token's buffers are the live ring, by reference
    assert tok2["_echo_buffers"] is res._buffers


# -- pipeline integration: accounting + one dispatch per step ----------------


def test_emit_draws_preserves_exact_echo_accounting():
    reg.reset()
    frames = 4 * B
    with EchoingPipeline(
        _batches(4, delay=0.01), capacity=32, max_echo_factor=8,
        augment=None, emit_draws=True,
    ) as pipe:
        step = make_echo_fused_step(reservoir_draw=pipe.reservoir.draw)
        state = make_train_state(
            CubeRegressor(), np.zeros((B, H, W, 4), np.uint8),
            optimizer=optax.sgd(0.01),
        )
        steps = 0
        for token in pipe:
            state, _ = step(state, token)
            steps += 1
    st = pipe.stats
    assert st["inserted"] == frames
    assert st["steps"] == steps == 4 * 8  # full budget drained
    assert st["fresh"] + st["echoed"] == steps * B
    assert st["fresh"] == frames
    counters = reg.report()["counters"]
    assert counters["echo.fresh"] + counters["echo.echoed"] == steps * B
    assert (pipe._use[pipe._filled] <= 8).all()


def test_fused_driver_one_dispatch_per_step_single_chip():
    """EchoingPipeline(emit_draws) -> make_echo_fused_step ->
    TrainDriver: exactly ONE device dispatch per step — no standalone
    echo.sample jit, no decode.dispatch."""
    reg.reset()
    with EchoingPipeline(
        _batches(4), capacity=32, max_echo_factor=4, emit_draws=True,
    ) as pipe:
        step = make_echo_fused_step(reservoir_draw=pipe.reservoir.draw)
        state = make_train_state(
            CubeRegressor(), np.zeros((B, H, W, 4), np.uint8),
            optimizer=optax.sgd(0.01),
        )
        drv = TrainDriver(step, state, inflight=2, sync_every=0)
        state, final = drv.run(pipe)
    st = pipe.stats
    assert drv.stats["steps"] == st["steps"] == 4 * 4
    spans = reg.spans()
    assert spans["train.dispatch"]["count"] == drv.stats["steps"]
    assert "echo.sample" not in spans  # the gather rides the train jit
    assert "decode.dispatch" not in spans
    calls = spans["train.dispatch"]["count"] + sum(
        spans.get(k, {}).get("count", 0)
        for k in ("echo.sample", "decode.dispatch")
    )
    assert calls / drv.stats["steps"] == 1.0
    assert isinstance(final, float) and np.isfinite(final)
    # the driver's image accounting reads the token's host index vector
    assert drv.stats["images_retired"] == drv.stats["steps"] * B


def test_fused_mesh_one_dispatch_per_step_8_devices():
    """The same contract on the 8-device mesh: sharded ring, pinned
    state/buffer layouts, one dispatch per step."""
    from blendjax.parallel import create_mesh
    from blendjax.train import MeshTrainDriver, make_mesh_echo_fused_step

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    mesh = create_mesh({"data": -1})
    gb = 8  # batch divides the 8-way data axis
    reg.reset()

    def batches(n):
        for i in range(n):
            rng = np.random.default_rng(100 + i)
            yield {
                "image": rng.integers(0, 255, (gb, H, W, 4), np.uint8),
                "xy": (rng.random((gb, 8, 2)) * H).astype(np.float32),
            }

    state = make_train_state(
        CubeRegressor(features=(4,), dtype=jnp.float32),
        np.zeros((gb, H, W, 4), np.uint8), mesh=mesh,
    )
    with EchoingPipeline(
        batches(4), capacity=32, max_echo_factor=4,
        emit_draws=True, mesh=mesh,
    ) as pipe:
        step = make_mesh_echo_fused_step(state, mesh, pipe.reservoir)
        drv = MeshTrainDriver(step, state, mesh, inflight=2, sync_every=0)
        state, final = drv.run(pipe)
    assert drv.chips == 8
    st = pipe.stats
    assert st["fresh"] + st["echoed"] == st["steps"] * gb
    spans = reg.spans()
    assert spans["train.dispatch"]["count"] == drv.stats["steps"]
    assert "echo.sample" not in spans
    assert "decode.dispatch" not in spans
    assert np.isfinite(final)


def test_buffer_sharding_pin_holds_without_state_sharding():
    """buffer_sharding= must pin the ring layout even when no state
    sharding is given (a buffer-only caller must not silently lose the
    fail-loudly guarantee): the pinned step runs on a correctly-placed
    ring and REJECTS a drifted (replicated) one at dispatch instead of
    silently resharding it."""
    from blendjax.parallel import create_mesh
    from blendjax.parallel.sharding import replicated, ring_sharding

    mesh = create_mesh({"data": -1})
    res = SampleReservoir(
        capacity=16, augment=None, sharding=ring_sharding(mesh)
    )
    res.insert(_batch(0, b=8))
    state = make_train_state(
        CubeRegressor(features=(8,)), np.zeros((8, H, W, 4), np.uint8),
        optimizer=optax.sgd(0.01),
    )
    step = make_echo_fused_step(
        reservoir_draw=res.draw, donate=False,
        buffer_sharding=res.sharding,
    )
    state, m = step(state, res.draw_token(np.arange(8)))
    assert np.isfinite(float(np.asarray(m["loss"])))
    drifted = jax.device_put(
        {k: np.asarray(v) for k, v in res._buffers.items()},
        replicated(mesh),
    )
    token = res.draw_token(np.arange(8))
    token["_echo_buffers"] = drifted
    with pytest.raises(Exception, match="[Ss]harding"):
        out = step(state, token)
        jax.block_until_ready(out[1]["loss"])


def test_mesh_echo_fused_step_requires_sharded_ring():
    from blendjax.parallel import create_mesh
    from blendjax.train import make_mesh_echo_fused_step

    mesh = create_mesh({"data": -1})
    state = make_train_state(
        CubeRegressor(features=(4,)), np.zeros((8, H, W, 4), np.uint8),
        mesh=mesh,
    )
    unsharded = SampleReservoir(capacity=8, augment=None)
    with pytest.raises(ValueError, match="mesh"):
        make_mesh_echo_fused_step(state, mesh, unsharded)


# -- donation: ring stability + state reuse under the fused step --------------


def test_reservoir_buffers_stable_under_fused_donation():
    """The fused step DONATES the state but only READS the ring: across
    inserts, token draws, and donated fused steps the ring's device
    pointers never move — and the donated state writes back into the
    same buffers it consumed (one state copy for the whole run)."""
    audit = DonationAudit()
    with EchoingPipeline(
        _batches(4), capacity=16, max_echo_factor=4, emit_draws=True,
    ) as pipe:
        step = make_echo_fused_step(reservoir_draw=pipe.reservoir.draw)
        state = make_train_state(
            CubeRegressor(features=(8,)),
            np.zeros((B, H, W, 4), np.uint8), optimizer=optax.sgd(0.01),
        )
        it = iter(pipe)
        state, _ = step(state, next(it))  # compile + first donation
        audit.snapshot("state", state.params)
        audit.snapshot("ring", pipe.reservoir._buffers)
        for token in it:
            state, m = step(state, token)
            audit.snapshot("ring", pipe.reservoir._buffers)
        jax.block_until_ready(m["loss"])
        audit.snapshot("state", state.params)
    audit.assert_stable("ring")
    audit.assert_stable("state")
    rep = audit.report()
    assert rep["ring"]["stable"] and rep["state"]["stable"]
    assert rep["ring"]["snapshots"] >= 2


def test_donation_audit_reports_a_moved_buffer():
    """The audit itself must catch a copy: an UNDONATED update chain
    allocates fresh buffers, and the audit says so."""
    x = jnp.arange(1024.0)
    f = jax.jit(lambda v: v + 1)  # no donation: output is a new buffer
    audit = DonationAudit()
    audit.snapshot("x", x)
    y = f(x)
    audit.snapshot("x", y)
    assert not audit.stable("x")
    with pytest.raises(AssertionError, match="moved"):
        audit.assert_stable("x")
