"""Consumer RL layer against real launched producers (reference
``tests/test_env.py`` semantics, headless)."""

import os

import numpy as np
import pytest

from blendjax.env import BatchedRemoteEnv, create_renderer, launch_env
from blendjax.env.remote import _kwargs_to_cli

CARTPOLE = os.path.join(
    os.path.dirname(__file__), "..", "examples", "control",
    "cartpole_producer.py",
)


def test_kwargs_to_cli():
    argv = _kwargs_to_cli(
        {"real_time": True, "render_every": 2, "flag_off": False,
         "items": [1, 2]}
    )
    assert argv == [
        "--real-time", "--render-every", "2", "--no-flag-off",
        "--items", "1", "2",
    ]


def test_launch_env_reset_step_episodes():
    with launch_env(script=CARTPOLE, seed=5) as env:
        obs, info = env.reset()
        assert np.asarray(obs).shape == (4,)
        assert env.env_time is not None
        # drive with the P-controller: pole stays up for 50 steps
        for _ in range(50):
            x, x_dot, th, th_dot = np.asarray(obs, np.float32)
            obs, reward, done, info = env.step(
                float(8 * th + th_dot + 0.2 * x)
            )
            assert reward == 1.0 and not done
        # drive it over: full push makes the pole fall eventually
        fell = False
        for _ in range(400):
            obs, reward, done, info = env.step(5.0)
            if done:
                fell = True
                break
        assert fell and reward == 0.0
        # reset starts a fresh episode
        obs, _ = env.reset()
        _, reward, done, _ = env.step(0.0)
        assert not done and reward == 1.0


def test_render_rgb_array_rides_along():
    with launch_env(script=CARTPOLE, seed=1, render_every=1) as env:
        env.reset()
        env.step(0.0)
        rgb = env.render(mode="rgb_array")
        assert rgb is not None and rgb.shape == (240, 320, 4)
        # headless human-mode rendering collects into the array backend
        env.render(mode="human", backend="array")
        assert len(env._viewer.frames) == 1


def test_array_renderer_registry():
    r = create_renderer("array")
    r.imshow(np.zeros((2, 2, 3)))
    assert len(r.frames) == 1
    r.close()
    assert r.frames == []


def test_batched_envs_lockstep_and_autoreset():
    with BatchedRemoteEnv(script=CARTPOLE, num_envs=2, seed=0) as venv:
        obs, infos = venv.reset()
        assert obs.shape == (2, 4) and len(infos) == 2
        done_seen = False
        for _ in range(150):
            obs, reward, done, infos = venv.step(np.full(2, 5.0))
            assert obs.shape == (2, 4) and reward.shape == (2,)
            if done.any():
                done_seen = True
                break
        assert done_seen
        # after auto-reset the returned obs belongs to a fresh episode
        obs2, reward2, done2, _ = venv.step(np.zeros(2))
        assert not done2.all()


def test_kwargs_to_cli_bool_list_round_trip():
    """The producer side parses these back with argparse: booleans via
    paired --flag/--no-flag actions, lists via nargs — the round trip
    the RL launch path depends on."""
    import argparse

    argv = _kwargs_to_cli(
        {"real_time": True, "render_every": 3, "shadows": False,
         "shape": [240, 320]}
    )
    ap = argparse.ArgumentParser()
    ap.add_argument("--real-time", action="store_true", default=False)
    ap.add_argument("--no-real-time", dest="real_time",
                    action="store_false")
    ap.add_argument("--render-every", type=int, default=0)
    ap.add_argument("--shadows", action="store_true", default=True)
    ap.add_argument("--no-shadows", dest="shadows", action="store_false")
    ap.add_argument("--shape", type=int, nargs=2)
    opts = ap.parse_args(argv)
    assert opts.real_time is True
    assert opts.render_every == 3
    assert opts.shadows is False
    assert opts.shape == [240, 320]


def test_batched_step_parks_final_observation_in_infos():
    """The vector-env auto-reset contract: a done row's TERMINAL
    observation rides in infos[i]['final_observation'] while the
    stacked obs holds the fresh episode's first observation —
    bootstrapped TD targets depend on the distinction."""
    with BatchedRemoteEnv(script=CARTPOLE, num_envs=2, seed=0) as venv:
        venv.reset()
        for _ in range(200):
            obs, reward, done, infos = venv.step(np.full(2, 5.0))
            if done.any():
                break
        assert done.any(), "no episode ended under a full push"
        for i in range(2):
            if done[i]:
                fin = np.asarray(infos[i]["final_observation"],
                                 np.float32)
                assert fin.shape == (4,)
                # the terminal state is past the fail bound; the fresh
                # episode's start is near upright — they must differ
                assert abs(fin[2]) > 0.4 or abs(fin[0]) > 3.0
                start = np.asarray(obs[i], np.float32)
                assert abs(start[2]) <= 0.05 and abs(start[0]) <= 0.05
            else:
                assert "final_observation" not in infos[i]


def test_batched_lockstep_is_deterministic_under_thread_pool():
    """Two fleets, same seeds, same action sequence -> identical
    trajectories: the thread pool overlaps RPCs but preserves env[i] ->
    result[i] ordering (lockstep), and seeded resets pin the episode
    RNG on every producer."""

    def rollout():
        with BatchedRemoteEnv(script=CARTPOLE, num_envs=2,
                              seed=0) as venv:
            obs, _ = venv.reset(seed=123)
            trace = [obs]
            rng = np.random.default_rng(7)
            for _ in range(20):
                obs, reward, done, _ = venv.step(
                    rng.uniform(-1, 1, size=2)
                )
                trace.append(obs)
            return np.stack(trace)

    a = rollout()
    b = rollout()
    np.testing.assert_array_equal(a, b)


def test_batched_close_is_idempotent():
    venv = BatchedRemoteEnv(script=CARTPOLE, num_envs=2, seed=0)
    venv.reset()
    venv.step(np.zeros(2))
    venv.close()
    venv.close()  # second close must be a no-op, not a crash


def test_remote_reset_seed_determinism():
    with launch_env(script=CARTPOLE, seed=5, proto="ipc") as env:
        o1, _ = env.reset(seed=77)
        env.step(1.0)  # leave STATE_INIT so the next reset rewinds
        o2, _ = env.reset(seed=77)
        o3, _ = env.reset(seed=78)
        np.testing.assert_allclose(
            np.asarray(o1), np.asarray(o2), atol=0
        )
        assert not np.allclose(np.asarray(o2), np.asarray(o3))


@pytest.mark.skipif(
    pytest.importorskip("gymnasium") is None, reason="gymnasium missing"
)
def test_gymnasium_reset_seed_reaches_the_producer():
    """Gymnasium's reset(seed=) contract must cross the wire: the
    PRODUCER's episode RNG decides the initial state, so seeding only
    the local np_random would leave seeded resets nondeterministic."""
    from blendjax.env import GymnasiumRemoteEnv

    env = GymnasiumRemoteEnv(script=CARTPOLE, seed=9, proto="ipc")
    try:
        o1, _ = env.reset(seed=42)
        env.step(np.zeros(1, np.float32))
        o2, _ = env.reset(seed=42)
        o3, _ = env.reset(seed=43)
        np.testing.assert_array_equal(o1, o2)
        assert not np.array_equal(o2, o3)
    finally:
        env.close()


@pytest.mark.skipif(
    pytest.importorskip("gymnasium") is None, reason="gymnasium missing"
)
def test_gymnasium_adapter_api():
    import gymnasium

    from blendjax.env import GymnasiumRemoteEnv

    env = GymnasiumRemoteEnv(
        script=CARTPOLE,
        observation_space=gymnasium.spaces.Box(
            -np.inf, np.inf, (4,), np.float32
        ),
        action_space=gymnasium.spaces.Box(-5, 5, (1,), np.float32),
        max_episode_steps=10,
        seed=2,
    )
    try:
        obs, info = env.reset()
        assert obs.shape == (4,) and obs.dtype == np.float32
        truncated = False
        for _ in range(10):
            obs, reward, terminated, truncated, info = env.step(
                np.zeros(1, np.float32)
            )
            if terminated or truncated:
                break
        assert truncated or terminated
    finally:
        env.close()


@pytest.mark.skipif(
    pytest.importorskip("gymnasium") is None, reason="gymnasium missing"
)
def test_gymnasium_make_registry_round_trip():
    """Registry parity (reference ``cartpole_gym/__init__.py:3-6``):
    ``gymnasium.make`` on the registered id launches and steps the
    headless cartpole; the legacy blendtorch-shaped alias resolves to
    the same factory."""
    import gymnasium

    import blendjax.env  # noqa: F401  (import registers the envs)

    assert "blendjax/Cartpole-v0" in gymnasium.registry
    assert "blendtorch-cartpole-v0" in gymnasium.registry
    spec = gymnasium.registry["blendtorch-cartpole-v0"]
    assert spec.entry_point == gymnasium.registry[
        "blendjax/Cartpole-v0"
    ].entry_point

    env = gymnasium.make("blendjax/Cartpole-v0", seed=4, proto="ipc")
    try:
        obs, info = env.reset()
        assert np.asarray(obs).shape == (4,)
        for _ in range(5):
            obs, reward, terminated, truncated, info = env.step(
                np.zeros(1, np.float32)
            )
            assert reward == 1.0 and not terminated and not truncated
    finally:
        env.close()


@pytest.mark.skipif(
    pytest.importorskip("gymnasium") is None, reason="gymnasium missing"
)
def test_openai_compat_shim_classic_call_shape():
    """OpenAIRemoteEnv restores the reference's classic-gym call shape
    (``btt/env.py:195-313``): reset -> obs, step -> (obs, reward, done,
    info) — for code migrating from blendtorch."""
    import gymnasium

    from blendjax.env import OpenAIRemoteEnv

    env = OpenAIRemoteEnv(
        script=CARTPOLE,
        observation_space=gymnasium.spaces.Box(
            -np.inf, np.inf, (4,), np.float32
        ),
        action_space=gymnasium.spaces.Box(-5, 5, (1,), np.float32),
        max_episode_steps=5,
        seed=3,
    )
    try:
        obs = env.reset()
        assert isinstance(obs, np.ndarray) and obs.shape == (4,)
        done = False
        steps = 0
        while not done:
            out = env.step(np.zeros(1, np.float32))
            assert len(out) == 4
            obs, reward, done, info = out
            steps += 1
            assert steps <= 5
        assert steps >= 1
    finally:
        env.close()
