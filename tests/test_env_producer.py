"""Producer env semantics: reset/step/reward/done across episodes, driven
through the real REQ/REP rendezvous (reference ``tests/test_env.py:12-43``
with ``env.blend.py``'s minimal rotate-the-cube env, headless here)."""

import threading

import numpy as np
import pytest

from blendjax.producer.animation import Engine
from blendjax.producer.env import BaseEnv, RemoteControlledAgent
from blendjax.transport import RpcClient


class CounterEngine(Engine):
    """Minimal 'physics': integrates the applied action each frame
    (the headless analog of the reference's rotate-the-cube test env,
    ``tests/blender/env.blend.py:7-47``)."""

    def __init__(self):
        self.value = 0.0
        self.pending = 0.0

    def frame_set(self, frame):
        self.value += self.pending

    def reset(self):
        self.value = 0.0
        self.pending = 0.0


class CounterEnv(BaseEnv):
    def __init__(self, agent, engine):
        super().__init__(agent)
        self.engine = engine

    def _env_reset(self):
        self.engine.reset()

    def _env_prepare_step(self, action):
        self.engine.pending = float(action)

    def _env_post_step(self):
        v = self.engine.value
        return {
            "obs": np.array([v], np.float32),
            "reward": float(v),
            "done": bool(v >= 3.0),
        }


@pytest.fixture
def running_env():
    engine = CounterEngine()
    agent = RemoteControlledAgent("tcp://127.0.0.1:*", timeoutms=200)
    env = CounterEnv(agent, engine)
    t = threading.Thread(target=env.run, args=(engine,), daemon=True)
    t.start()
    client = RpcClient(agent.addr, timeoutms=10000)
    yield client
    env.stop()
    client.close()
    t.join(timeout=10)


def test_reset_step_reward_done_two_episodes(running_env):
    client = running_env
    rep = client.call(cmd="reset")
    np.testing.assert_allclose(rep["obs"], [0.0])
    for expected in (1.0, 2.0, 3.0):
        rep = client.call(cmd="step", action=1.0)
        np.testing.assert_allclose(rep["obs"], [expected])
        assert rep["reward"] == expected
        assert rep["done"] is (expected >= 3.0)
    # episode 2: reset rewinds the simulation
    rep = client.call(cmd="reset")
    np.testing.assert_allclose(rep["obs"], [0.0])
    rep = client.call(cmd="step", action=2.0)
    np.testing.assert_allclose(rep["obs"], [2.0])
    assert rep["done"] is False
    assert "time" in rep  # sim time = frame id rides along


def test_unknown_command_gets_error_reply(running_env):
    client = running_env
    rep = client.call(cmd="bogus")
    assert "error" in rep
    # the env survives and still services valid requests afterwards
    rep = client.call(cmd="reset")
    np.testing.assert_allclose(rep["obs"], [0.0])
