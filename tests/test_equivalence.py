"""Numeric-equivalence gates for every sharded parallelism mode.

A wrong-math sharding rule passes shape/finiteness checks with a
plausible, finite, WRONG loss (VERDICT r3 weak #3) — so each mode must
reproduce a single-device run of the identical model/batch: dp x tp x sp
(column-sharded dense + ring attention) and FSDP against the unsharded
StreamFormer, and MoE top-1 routing against a per-token dense reference.

The contract itself (tolerances, comparison scaffold, dense MoE
reference) lives in :mod:`blendjax.testing.equivalence`, shared with
``__graft_entry__.dryrun_multichip`` so the dry-run gate and this suite
can never assert different contracts.

All comparisons run in float32 (the bf16 compute path is covered by the
same code; bf16 would only loosen tolerances, not exercise different
sharding rules).
"""

import numpy as np

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from blendjax.models import StreamFormer  # noqa: E402
from blendjax.parallel import create_mesh  # noqa: E402
from blendjax.testing.equivalence import (  # noqa: E402
    assert_sharded_matches_single_device,
    moe_per_token_reference,
)
from blendjax.train import make_train_state  # noqa: E402

BATCH, H, W = 8, 32, 32


def _data(seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 255, (BATCH, H, W, 4), np.uint8)
    xy = (rng.random((BATCH, 8, 2)) * W).astype(np.float32)
    return images, xy


def _model(**kw):
    return StreamFormer(
        patch=8, dim=32, depth=2, num_heads=4, num_outputs=16,
        dtype=jnp.float32, **kw,
    )


def test_dp_tp_sp_matches_single_device():
    """dp x tp x sp: batch on data, dense kernels column-sharded on
    tensor, ring attention over seq — same loss/grads as one device."""
    mesh = create_mesh({"data": 2, "tensor": 2, "seq": 2})
    images, xy = _data()
    assert_sharded_matches_single_device(
        _model(use_ring=True, mesh=mesh, remat=True), _model(),
        mesh, images, xy,
    )


def test_ulysses_sp_matches_single_device():
    mesh = create_mesh({"data": 2, "tensor": 2, "seq": 2})
    images, xy = _data()
    assert_sharded_matches_single_device(
        _model(use_ring=True, mesh=mesh, sp_mode="ulysses"), _model(),
        mesh, images, xy,
    )


def test_fsdp_matches_single_device():
    """data x fsdp: parameters sharded over fsdp (ZeRO-3-style), batch
    over data x fsdp folded — same loss/grads as one device."""
    mesh = create_mesh({"data": 4, "fsdp": 2})
    images, xy = _data()
    state = make_train_state(_model(), images, mesh=mesh)
    specs = [
        getattr(v.sharding, "spec", ())
        for v in jax.tree_util.tree_leaves(state.params)
    ]
    assert any("fsdp" in (s or ()) for s in specs)  # mode is really on
    assert_sharded_matches_single_device(
        _model(), _model(), mesh, images, xy
    )


def test_moe_top1_matches_per_token_dense_reference():
    """MoE top-1 routing: every token's output equals gate * its
    argmax-expert's dense MLP applied to that token alone (capacity
    ample so nothing drops) — einsum dispatch/combine is pure routing,
    not an approximation."""
    from blendjax.models import MoEMLP

    b, t, c, e = 2, 8, 16, 4
    rng = np.random.default_rng(1)
    x = rng.normal(size=(b, t, c)).astype(np.float32)
    moe = MoEMLP(num_experts=e, mlp_ratio=2, capacity_factor=float(e),
                 dtype=jnp.float32)
    variables = moe.init(jax.random.key(0), x)
    y = np.asarray(moe.apply(variables, x))
    expected = moe_per_token_reference(variables["params"], x)
    np.testing.assert_allclose(y, expected, atol=1e-5)


def test_moe_top1_dense_reference_expert_sharded():
    """The same per-token contract holds with expert-sharded params on
    a data x expert mesh (GSPMD all-to-all dispatch is still routing)."""
    from blendjax.models import MoEMLP
    from blendjax.parallel import shard_params
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"data": 2, "expert": 4})
    b, t, c, e = 4, 8, 16, 4
    rng = np.random.default_rng(2)
    x = rng.normal(size=(b, t, c)).astype(np.float32)
    moe = MoEMLP(num_experts=e, mlp_ratio=2, capacity_factor=float(e),
                 dtype=jnp.float32)
    variables = moe.init(jax.random.key(0), x)
    expected = moe_per_token_reference(variables["params"], x)

    sharded = {"params": shard_params(mesh, variables["params"])}
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    y_sh = np.asarray(jax.jit(lambda v, x: moe.apply(v, x))(sharded, xs))
    np.testing.assert_allclose(y_sh, expected, atol=1e-5)
