"""Every example entry point runs in the default suite.

The reference's examples are its de-facto integration tests
(``examples/datagen/generate.py``, ``examples/control/cartpole.py``);
blendjax's previously ran only when a human ran them — a one-flag
regression in an entry script would ship (VERDICT r3 weak #4). Each test
executes the real ``main()`` (argparse and all) with tiny sizes, in
process, so the launcher/pipeline/train wiring the scripts exercise is
the production path.
"""

import importlib.util
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_example(relpath):
    path = os.path.join(ROOT, "examples", relpath)
    name = "example_" + relpath.replace(os.sep, "_").replace("/", "_")[:-3]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_main(monkeypatch, relpath, *argv):
    mod = load_example(relpath)
    monkeypatch.setattr(
        sys, "argv", [os.path.join(ROOT, "examples", relpath), *argv]
    )
    mod.main()
    return mod


def test_minimal(capsys):
    load_example("datagen/minimal.py").main()
    out = capsys.readouterr().out
    assert out.count("batch ") == 5 and "image(8, " in out


def test_datagen_train_raw(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "3", "--instances", "1", "--batch", "8",
        "--shape", "64", "64",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out


def test_datagen_train_tile_chunk_augment(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "2", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--encoding", "tile", "--chunk", "2",
        "--augment",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out


def test_datagen_train_pal_chunk(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "2", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--encoding", "pal", "--chunk", "2",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out


def test_datagen_train_echo(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "6", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--echo", "4", "--echo-capacity", "32",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out
    assert "echo={" in out and "'fresh':" in out
    assert "doctor:" in out


def test_datagen_train_synthetic_fleet(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "4", "--batch", "8", "--shape", "32", "32",
        "--synthetic-producers", "1", "--fleet", "1:2",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out
    assert "doctor:" in out
    # the scale-event log prints beside the verdict at exit
    assert "fleet: instances=" in out and "(bounds 1:2)" in out


def test_datagen_train_checkpoint_then_resume(monkeypatch, capsys,
                                              tmp_path):
    ckpt = str(tmp_path / "snapshots")
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "4", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--checkpoint", ckpt,
        "--checkpoint-every", "2",
    )
    out = capsys.readouterr().out
    assert "checkpoints in" in out and "steps [2, 4]" in out
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "2", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--checkpoint", ckpt, "--resume",
    )
    out = capsys.readouterr().out
    assert "resumed from snapshot step 4" in out
    assert "images/sec" in out


def test_datagen_train_record_then_replay(monkeypatch, capsys, tmp_path):
    prefix = str(tmp_path / "rec")
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "3", "--instances", "1", "--batch", "8",
        "--shape", "64", "64", "--record", prefix,
    )
    assert any(p.name.startswith("rec_") for p in tmp_path.iterdir())
    run_main(
        monkeypatch, "datagen/train.py",
        "--steps", "3", "--batch", "8", "--shape", "64", "64",
        "--replay", prefix,
    )
    out = capsys.readouterr().out
    assert out.count("images/sec") == 2


def test_train_transformer(monkeypatch, capsys):
    run_main(
        monkeypatch, "datagen/train_transformer.py",
        "--steps", "2", "--instances", "1", "--batch", "8",
        "--shape", "32", "32", "--patch", "8", "--dim", "32",
        "--depth", "1", "--heads", "2",
    )
    out = capsys.readouterr().out
    assert "step 0: loss=" in out and "images/sec" in out


def test_cartpole_controller(monkeypatch, capsys):
    pytest.importorskip("gymnasium")  # the example drives gymnasium.make
    mod = load_example("control/cartpole.py")
    mod.main(steps_total=40)
    out = capsys.readouterr().out
    assert "final:" in out or "episode end" in out


def test_train_reinforce(monkeypatch, capsys):
    run_main(
        monkeypatch, "control/train_reinforce.py",
        "--iters", "2", "--horizon", "8", "--envs", "2",
    )
    out = capsys.readouterr().out
    assert "iter 0:" in out and "iter 1:" in out


def test_train_dqn(monkeypatch, capsys):
    run_main(
        monkeypatch, "control/train_dqn.py",
        "--steps", "24", "--envs", "2", "--batch", "8",
        "--capacity", "64",
    )
    out = capsys.readouterr().out
    assert "final:" in out and "mean_return=" in out


def test_train_dqn_checkpoint_then_resume(monkeypatch, capsys, tmp_path):
    """The RL resume path end to end at example scale: train with the
    session store armed, then resume and CONTINUE to a larger budget
    (docs/rl.md 'Checkpoint and resume')."""
    ckpt = str(tmp_path / "rl-ckpt")
    run_main(
        monkeypatch, "control/train_dqn.py",
        "--steps", "16", "--envs", "2", "--batch", "8",
        "--capacity", "64", "--checkpoint", ckpt, "--ckpt-every", "4",
    )
    capsys.readouterr()
    run_main(
        monkeypatch, "control/train_dqn.py",
        "--steps", "24", "--envs", "2", "--batch", "8",
        "--capacity", "64", "--checkpoint", ckpt, "--resume",
    )
    out = capsys.readouterr().out
    assert "resumed at step" in out and "final:" in out


def test_densityopt(monkeypatch, capsys):
    run_main(
        monkeypatch, "densityopt/densityopt.py",
        "--iters", "2", "--samples", "2", "--instances", "1",
    )
    out = capsys.readouterr().out
    assert "iter 0:" in out and "mu=" in out
