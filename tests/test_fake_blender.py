"""Hermetic Blender tier: the six ``tests/blender/*.blend.py`` fixtures
run through the PRODUCTION ``discover_blender`` + ``BlenderLauncher``
path against the fake Blender CLI (``blendjax.testing.fake_blender``) —
no real Blender required. Mirrors ``test_blender.py`` (which stays the
opt-in ground-truth tier against a real install; reference CI,
``.travis.yml:15-24``)."""

import os
import stat
import sys

import numpy as np
import pytest

if sys.platform == "win32":  # pragma: no cover
    pytest.skip("fake blender wrapper is a POSIX shell script",
                allow_module_level=True)

from blendjax.launcher.finder import discover_blender
from blendjax.testing import write_fake_blender

FIXTURES = os.path.join(os.path.dirname(__file__), "blender")


def _script(name: str) -> str:
    return os.path.join(FIXTURES, name)


@pytest.fixture(scope="module")
def fake_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fake-blender-bin"))
    write_fake_blender(d)
    return d


# -- finder (reference ``btt/finder.py:16-76``) -----------------------------


def test_finder_discovers_fake_blender(fake_dir):
    info = discover_blender(additional_blender_paths=[fake_dir])
    assert info is not None
    assert info["path"] == os.path.join(fake_dir, "blender")
    assert (info["major"], info["minor"]) == (3, 6)
    # this interpreter has zmq + msgpack -> tensor codec detected
    assert info["codec"] == "tensor"


def test_finder_rejects_unparseable_version(tmp_path):
    exe = tmp_path / "blender"
    exe.write_text("#!/bin/sh\necho 'not a version line'\n")
    exe.chmod(exe.stat().st_mode | stat.S_IXUSR)
    assert discover_blender(additional_blender_paths=[str(tmp_path)]) is None


def test_finder_rejects_failing_python_smoke(tmp_path):
    exe = tmp_path / "blender"
    # versions fine, but the embedded-python smoke prints no BJX-OK
    exe.write_text(
        "#!/bin/sh\n"
        'case "$*" in *--version*) echo "Blender 4.2.0";;'
        ' *) echo "ImportError: no module named zmq" >&2;; esac\n'
    )
    exe.chmod(exe.stat().st_mode | stat.S_IXUSR)
    assert discover_blender(additional_blender_paths=[str(tmp_path)]) is None


def test_finder_missing_returns_none(tmp_path):
    saved = os.environ.get("PATH")
    try:
        os.environ["PATH"] = str(tmp_path)  # nothing on PATH at all
        assert discover_blender() is None
    finally:
        os.environ["PATH"] = saved


# -- the six fixture pairings (mirrors test_blender.py) ---------------------


def _launcher(fake_dir, script, **kwargs):
    from blendjax.launcher import BlenderLauncher

    return BlenderLauncher(
        script=_script(script), background=True, blend_path=[fake_dir],
        **kwargs,
    )


def test_fake_blender_launcher_handshake(fake_dir):
    from blendjax.data.stream import RemoteStream

    with _launcher(
        fake_dir, "launcher.blend.py",
        num_instances=2, named_sockets=["DATA"], seed=10,
        instance_args=[["--x", "a"], ["--x", "b"]],
    ) as launcher:
        got = {}
        for msg in RemoteStream(
            launcher.addresses["DATA"], timeoutms=60_000, max_items=2
        ):
            got[msg["btid"]] = msg
    assert sorted(got) == [0, 1]
    assert [got[i]["btseed"] for i in (0, 1)] == [10, 11]
    assert got[0]["remainder"] == ["--x", "a"]
    assert got[1]["remainder"] == ["--x", "b"]
    for i in (0, 1):
        assert got[i]["btsockets"] == ["DATA"]


def test_fake_blender_stream_ingest(fake_dir):
    from blendjax.data.stream import RemoteStream

    with _launcher(
        fake_dir, "dataset.blend.py",
        num_instances=1, named_sockets=["DATA"], seed=0,
    ) as launcher:
        frames = []
        for msg in RemoteStream(
            launcher.addresses["DATA"], timeoutms=60_000, max_items=16
        ):
            assert msg["img"].shape == (64, 64)
            assert (msg["img"] == msg["frameid"] % 251).all()
            frames.append(int(msg["frameid"]))
    assert sorted(frames) == sorted(list(range(1, 5)) * 4)


def test_fake_blender_duplex_echo(fake_dir):
    from blendjax.transport.channels import PairChannel

    with _launcher(
        fake_dir, "duplex.blend.py",
        num_instances=1, named_sockets=["CTRL"], seed=0,
    ) as launcher:
        duplex = PairChannel(
            launcher.addresses["CTRL"][0], btid=99, bind=False
        )
        try:
            mid = duplex.send(hello=[1, 2, 3])
            echo = duplex.recv(timeoutms=60_000)
            end = duplex.recv(timeoutms=60_000)
        finally:
            duplex.close()
    assert echo["echo"]["hello"] == [1, 2, 3]
    assert echo["echo"]["btid"] == 99
    assert echo["echo"]["btmid"] == mid
    assert echo["btid"] == 0
    assert end["msg"] == "end"


def test_fake_blender_animation_lifecycle(fake_dir):
    from blendjax.data.stream import RemoteStream

    with _launcher(
        fake_dir, "anim.blend.py",
        num_instances=1, named_sockets=["DATA"], seed=0,
    ) as launcher:
        (msg,) = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=1
            )
        )
    episode = (
        ["pre_animation"]
        + [s for f in (1, 2, 3) for s in (f"pre_frame:{f}", f"post_frame:{f}")]
        + ["post_animation"]
    )
    assert msg["seq"] == ["pre_play"] + episode * 2 + ["post_play"]


def test_fake_blender_remote_env(fake_dir):
    from blendjax.env.remote import RemoteEnv

    with _launcher(
        fake_dir, "env.blend.py",
        num_instances=1, named_sockets=["GYM"], seed=0,
        instance_args=[["--done-after", "5"]],
    ) as launcher:
        env = RemoteEnv(launcher.addresses["GYM"][0], timeoutms=60_000)
        try:
            for _ in range(2):
                obs, info = env.reset()
                assert obs == pytest.approx(0.0)
                done = False
                steps = 0
                while not done:
                    obs, reward, done, info = env.step(0.6)
                    assert obs == pytest.approx(0.6)
                    assert reward == pytest.approx(1.0)
                    steps += 1
                    assert steps < 50
                assert steps >= 1
        finally:
            env.close()


def test_fake_blender_camera_projection(fake_dir):
    from blendjax.data.stream import RemoteStream
    from blendjax.producer.camera import Camera

    with _launcher(
        fake_dir, "cam.blend.py",
        num_instances=1, named_sockets=["DATA"], seed=0,
    ) as launcher:
        (msg,) = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=1
            )
        )
    xyz = msg["xyz"]
    assert xyz.shape == (8, 3)

    pose = np.asarray(msg["proj_pose"])
    cam = Camera(
        position=pose[:3, 3], rotation=pose[:3, :3], shape=(480, 640),
        focal_mm=50.0, sensor_mm=36.0, clip_near=0.1, clip_far=100.0,
    )
    pix, z = cam.world_to_pixel(xyz, return_depth=True)
    np.testing.assert_allclose(pix, msg["proj_xy"], atol=1e-2)
    np.testing.assert_allclose(z, msg["proj_z"], atol=1e-4)

    pose_o = np.asarray(msg["ortho_pose"])
    cam_o = Camera(
        position=pose_o[:3, 3], rotation=pose_o[:3, :3], shape=(480, 640),
        ortho_scale=12.0, clip_near=0.1, clip_far=100.0,
    )
    pix_o, z_o = cam_o.world_to_pixel(xyz, return_depth=True)
    np.testing.assert_allclose(pix_o, msg["ortho_xy"], atol=1e-2)
    np.testing.assert_allclose(z_o, msg["ortho_z"], atol=1e-4)
    np.testing.assert_allclose(z_o, 10.0 - xyz[:, 2], atol=1e-4)


def test_fake_blender_runs_example_scene_background(fake_dir):
    """The REAL example scene script (examples/datagen/cube.blend.py)
    executes unmodified against the fake runtime's stock startup scene:
    --background streams corner annotations + frameids (offscreen is
    UI-only, like real Blender)."""
    from blendjax.data.stream import RemoteStream

    scene = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube.blend.py",
    )
    from blendjax.launcher import BlenderLauncher

    with BlenderLauncher(
        script=scene, background=True, blend_path=[fake_dir],
        num_instances=1, named_sockets=["DATA"], seed=7,
    ) as launcher:
        msgs = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=5
            )
        )
    for m in msgs:
        assert m["xy"].shape == (8, 2) and m["xy"].dtype == np.float32
        assert np.isfinite(m["xy"]).all()
        assert "image" not in m  # offscreen unsupported under --background


def test_fake_blender_runs_example_scene_ui_with_images(fake_dir):
    """UI mode (no --background): the same scene drives
    BpyAnimationDriver + OffScreenRenderer and streams rendered frames
    whose cube-corner splats sit at the published xy annotations."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher
    from blendjax.testing.fake_gpu import BACKGROUND

    scene = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube.blend.py",
    )
    with BlenderLauncher(
        script=scene, background=False, blend_path=[fake_dir],
        num_instances=1, named_sockets=["DATA"], seed=7,
    ) as launcher:
        msgs = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=3
            )
        )
    for m in msgs:
        img = m["image"]
        assert img.ndim == 3 and img.shape[-1] == 3  # mode="rgb"
        splats = np.argwhere((img != np.array(BACKGROUND[:3])).any(-1))
        assert len(splats) >= 1
        # every splat lies near a published corner annotation
        xy = m["xy"]
        for y, x in splats:
            d = np.abs(xy - np.array([x, y])).max(axis=1)
            assert d.min() < 3.0, f"splat ({y},{x}) far from xy"


def test_fake_blender_runs_supershape_scene(fake_dir):
    """The densityopt example scene (examples/densityopt/
    supershape.blend.py) executes unmodified against the fake runtime:
    procedural mesh via from_pydata/foreach_set, duplex-fed parameters,
    shape_id round-trip on the DATA stream."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher
    from blendjax.transport.channels import PairChannel

    scene = os.path.join(
        os.path.dirname(__file__), "..", "examples", "densityopt",
        "supershape.blend.py",
    )
    with BlenderLauncher(
        script=scene, background=True, blend_path=[fake_dir],
        num_instances=1, named_sockets=["DATA", "CTRL"], seed=0,
    ) as launcher:
        duplex = PairChannel(
            launcher.addresses["CTRL"][0], btid=99, bind=False
        )
        try:
            params = np.tile(
                np.array([7.0, 1, 1, 3, 3, 3], np.float64), (2, 2, 1)
            )
            duplex.send(
                shape_params=params, shape_ids=np.array([11, 22])
            )
            got = [
                int(m["shape_id"]) for m in RemoteStream(
                    launcher.addresses["DATA"], timeoutms=60_000,
                    max_items=2,
                )
            ]
        finally:
            duplex.close()
    assert got == [11, 22]  # params consumed in order, ids round-trip


def test_fake_blender_runs_cartpole_scene(fake_dir):
    """The RL example scene (examples/control/cartpole.blend.py) serves
    its env over the GYM RPC against the fake runtime's miniature
    rigid-body world: obs evolves under physics, the motor action moves
    the cart, and a tilted pole eventually ends the episode."""
    from blendjax.env.remote import RemoteEnv
    from blendjax.launcher import BlenderLauncher

    scene = os.path.join(
        os.path.dirname(__file__), "..", "examples", "control",
        "cartpole.blend.py",
    )
    with BlenderLauncher(
        script=scene, background=True, blend_path=[fake_dir],
        num_instances=1, named_sockets=["GYM"], seed=0,
    ) as launcher:
        env = RemoteEnv(launcher.addresses["GYM"][0], timeoutms=60_000)
        try:
            obs, info = env.reset()
            cart_x, pole_x, angle = obs
            assert abs(cart_x) < 1e-6 and abs(angle) <= 0.6
            done = False
            steps = 0
            while not done and steps < 400:
                obs, reward, done, info = env.step(30.0)  # push right
                steps += 1
            assert done, "pole never fell / cart never ran off"
            assert 1 <= steps < 400
            # pushing hard to the right moved the cart right before the
            # episode ended (or the pole tipped past 0.6 rad)
            cart_x, _, angle = obs
            assert cart_x > 0.0 or abs(angle) > 0.6
        finally:
            env.close()


def test_fake_blender_runs_falling_cubes_scene(fake_dir):
    """The falling-cubes datagen scene streams corner annotations whose
    vertical pixel coordinates descend as the cubes fall under the fake
    gravity (camera looks from above-side, default pose)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import BlenderLauncher

    scene = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "falling_cubes.blend.py",
    )
    with BlenderLauncher(
        script=scene, background=True, blend_path=[fake_dir],
        num_instances=1, named_sockets=["DATA"], seed=3,
    ) as launcher:
        msgs = list(
            RemoteStream(
                launcher.addresses["DATA"], timeoutms=60_000, max_items=12
            )
        )
    by_frame = sorted(msgs, key=lambda m: int(m["frameid"]))
    for m in by_frame:
        assert m["xy"].shape == (8 * 8, 2)  # 8 cubes x 8 corners
        assert np.isfinite(m["xy"]).all()
    # falling cubes: mean screen-y (upper-left origin) increases
    first = by_frame[0]["xy"][:, 1].mean()
    last = by_frame[-1]["xy"][:, 1].mean()
    assert last > first, (first, last)


def test_fake_blender_cli_python_expr(fake_dir):
    """The --python-expr path used by the finder smoke test executes in
    the stub's interpreter with fake bpy importable."""
    import subprocess

    out = subprocess.run(
        [os.path.join(fake_dir, "blender"), "--background",
         "--python-use-system-env", "--python-expr",
         "import bpy; print('fake?', bpy._is_fake)"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0
    assert "fake? True" in out.stdout
