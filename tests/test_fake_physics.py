"""Focused regressions for the fake runtime's math and rigid-body
semantics (blendjax.testing.fake_bpy) — locks in the contracts the
scene tests exercise indirectly: euler/matrix consistency, in-place
location tracking, and frame_set's rewind-vs-reevaluation rule."""

import math

import numpy as np
import pytest

from blendjax.testing import install_fake_bpy, reset_fake_bpy


@pytest.fixture()
def bpy():
    mod = install_fake_bpy(background=True)
    reset_fake_bpy(background=True)
    return mod


def test_euler_matrix_roundtrip(bpy):
    """to_euler('XYZ') inverts Euler.to_matrix3 across the non-gimbal
    range, including through object matrix_world with scale applied."""
    obj = bpy.data.objects.new("Probe")
    bpy.context.collection.objects.link(obj)
    rng = np.random.default_rng(0)
    for _ in range(50):
        e = rng.uniform((-np.pi, -np.pi / 2 + 0.1, -np.pi),
                        (np.pi, np.pi / 2 - 0.1, np.pi))
        obj.rotation_euler = e
        obj.scale = rng.uniform(0.5, 2.0, 3)
        got = obj.matrix_world.to_euler("XYZ")
        np.testing.assert_allclose(list(got), e, atol=1e-9)


def test_matrix_translation_tracks_location(bpy):
    obj = bpy.data.objects.new("Probe")
    bpy.context.collection.objects.link(obj)
    obj.location = (1.0, -2.0, 3.0)
    np.testing.assert_array_equal(
        obj.matrix_world.translation, [1.0, -2.0, 3.0]
    )


def _falling_cube(bpy, z=10.0):
    bpy.ops.rigidbody.world_add()
    bpy.ops.mesh.primitive_plane_add(size=40)
    bpy.ops.rigidbody.object_add(type="PASSIVE")
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, z))
    cube = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    return cube


def test_reevaluation_keeps_velocity_rewind_resets_it(bpy):
    """frame_set(frame_current) is a plain re-evaluation (dynamic state
    survives — the common depsgraph-refresh idiom); seeking backward is
    a rewind (velocities zero, like Blender resuming from the cache)."""
    cube = _falling_cube(bpy)
    scene = bpy.context.scene
    for f in range(2, 12):
        scene.frame_set(f)
    z10 = float(cube.location[2])
    v = scene._vel[id(cube)].copy()
    assert v[2] < 0  # falling

    scene.frame_set(scene.frame_current)  # re-evaluation: state kept
    np.testing.assert_array_equal(scene._vel[id(cube)], v)
    assert float(cube.location[2]) == z10

    scene.frame_set(12)  # continues from the kept velocity
    assert float(cube.location[2]) < z10

    cube.location = (0, 0, 10.0)
    scene.frame_set(1)  # rewind: velocities cleared
    assert id(cube) not in scene._vel
    scene.frame_set(2)
    # first post-rewind step starts from rest: the step RAN (nonzero
    # drop) but from zero velocity (small displacement only)
    assert 0.0 < 10.0 - float(cube.location[2]) < 0.1


def test_location_reference_tracks_hinge_body(bpy):
    """obj.location references stay live through physics (in-place
    mutation contract — a cached Vector tracks the object in Blender)."""
    bpy.ops.rigidbody.world_add()
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 1.0))
    cart = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 2.0))
    pole = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    hinge = bpy.data.objects.new("Hinge")
    hinge.location = (0, 0, 1.5)
    bpy.context.collection.objects.link(hinge)
    bpy.context.view_layer.objects.active = hinge
    bpy.ops.rigidbody.constraint_add(type="HINGE")
    hinge.rigid_body_constraint.object1 = cart
    hinge.rigid_body_constraint.object2 = pole

    pole.rotation_euler[1] = 0.3
    cached = pole.location  # grabbed BEFORE physics runs
    scene = bpy.context.scene
    for f in range(2, 10):
        scene.frame_set(f)
    assert cached is pole.location  # same live array
    assert abs(float(cached[0])) > 1e-3  # pendulum swung; cache tracked


def test_oversized_frame_jump_fails_loudly(bpy):
    """Seeks past the physics step guard raise instead of silently
    truncating the simulated span."""
    _falling_cube(bpy)
    scene = bpy.context.scene
    scene.frame_set(2)
    with pytest.raises(RuntimeError, match="frame jump"):
        scene.frame_set(scene.frame_current + 20_000)


def test_visibility_unaffected_by_default_scene_flag(bpy):
    """install/reset honor default_scene switching in place (prior
    imports keep working; the graph actually swaps)."""
    assert len(bpy.data.objects) == 0
    reset_fake_bpy(default_scene=True)
    assert "Cube" in bpy.data.objects and "Camera" in bpy.data.objects
    assert bpy.context.scene.camera is bpy.data.objects["Camera"]
    reset_fake_bpy(default_scene=False)
    assert len(bpy.data.objects) == 0


# ---------------------------------------------------------------------------
# Quantitative dynamics vs external ground truth (VERDICT r3 next #6).
#
# The reference's dynamics ground truth is Bullet-in-Blender
# (``cartpole.blend.py:38-43``); the hermetic stand-ins carry a stated
# accuracy contract instead (docs/architecture.md "Hermetic physics"):
# semi-implicit Euler against analytic closed forms, with asserted error
# bounds rather than labels.
# ---------------------------------------------------------------------------


def test_free_fall_matches_closed_form_kinematics(bpy):
    """z(n) = z0 - g dt^2 n(n+1)/2 exactly (semi-implicit Euler's
    discrete closed form), which tracks the continuous parabola
    z0 - g t^2/2 within the first-order bound g dt t / 2."""
    cube = _falling_cube(bpy, z=10.0)
    scene = bpy.context.scene
    g, dt = 9.81, 1.0 / scene.render.fps
    for f in range(2, 26):  # 24 steps = 1 simulated second at 24 fps
        scene.frame_set(f)
        n = f - 1
        t = n * dt
        z = float(cube.location[2])
        discrete = 10.0 - g * dt * dt * n * (n + 1) / 2.0
        assert abs(z - discrete) < 1e-9
        continuous = 10.0 - 0.5 * g * t * t
        assert abs(z - continuous) <= 0.5 * g * dt * t + 1e-9


def test_free_fall_rests_exactly_on_plane_surface(bpy):
    cube = _falling_cube(bpy, z=3.0)
    scene = bpy.context.scene
    for f in range(2, 60):
        scene.frame_set(f)
    # contact resolves to exact rest on the plane top + half extent
    assert float(cube.location[2]) == pytest.approx(0.5, abs=1e-12)
    assert np.all(scene._vel[id(cube)] == 0.0)


def _pendulum(bpy, L=1.0, psi0=0.05, fps=240):
    """Hinged bob hanging at angle pi + psi0 from the up axis."""
    bpy.ops.rigidbody.world_add()
    scene = bpy.context.scene
    scene.render.fps = fps
    bpy.ops.mesh.primitive_cube_add(size=0.1, location=(0, 0, 2.0 + L))
    bob = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    pivot = bpy.data.objects.new("Pivot")
    pivot.location = (0, 0, 2.0)
    bpy.context.collection.objects.link(pivot)
    bpy.context.view_layer.objects.active = pivot
    bpy.ops.rigidbody.constraint_add(type="HINGE")
    rc = pivot.rigid_body_constraint
    rc.object1 = None  # world-anchored pivot
    rc.object2 = bob
    bob.rotation_euler[1] = math.pi + psi0
    return bob, rc


def test_hinge_pendulum_small_angle_period(bpy):
    """Mean oscillation period matches the analytic small-angle
    pendulum 2*pi*sqrt(L/g) within 1% (tolerance budget: amplitude
    correction psi0^2/16 ~ 2e-4 + O((w*dt)^2) discretization)."""
    L, psi0, fps = 1.0, 0.05, 240
    bob, rc = _pendulum(bpy, L=L, psi0=psi0, fps=fps)
    scene = bpy.context.scene
    T_analytic = 2 * math.pi * math.sqrt(L / 9.81)
    frames = int(5 * T_analytic * fps)
    psis = []
    for f in range(2, 2 + frames):
        scene.frame_set(f)
        psis.append(float(bob.rotation_euler[1]) - math.pi)
    psis = np.asarray(psis)
    times = np.arange(1, frames + 1) / fps
    up = np.where((psis[:-1] < 0) & (psis[1:] >= 0))[0]
    # linear interpolation of each upward zero crossing
    cross = times[up] + (-psis[up]) / (psis[up + 1] - psis[up]) / fps
    assert len(cross) >= 4
    T = float(np.mean(np.diff(cross)))
    assert abs(T - T_analytic) / T_analytic < 0.01


def test_hinge_pendulum_energy_bounded_no_decay(bpy):
    """Semi-implicit Euler is symplectic: pendulum energy oscillates in
    a bounded band (< 5% of the amplitude energy over 2.5 periods)
    instead of drifting. Deliberate deviation from Bullet: no default
    damping, so energy does NOT decay — see docs/architecture.md."""
    L, psi0, fps = 1.0, 0.2, 240
    bob, rc = _pendulum(bpy, L=L, psi0=psi0, fps=fps)
    scene = bpy.context.scene
    g = 9.81
    E = []
    for f in range(2, 2 + 5 * fps):
        scene.frame_set(f)
        th = float(bob.rotation_euler[1])
        E.append(0.5 * (L * rc._omega) ** 2 + g * L * (1 + math.cos(th)))
    E = np.asarray(E)
    E_amp = g * L * (1 - math.cos(psi0))
    assert np.max(np.abs(E - E[0])) < 0.05 * E_amp


def test_slider_motor_integrates_velocity_exactly(bpy):
    """The slider motor is a velocity servo: x(n) = v*n*dt exactly, and
    the off-axis coordinates stay pinned."""
    bpy.ops.rigidbody.world_add()
    scene = bpy.context.scene
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 1.2))
    cart = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    motor = bpy.data.objects.new("Motor")
    motor.location = (0, 0, 1.2)
    bpy.context.collection.objects.link(motor)
    bpy.context.view_layer.objects.active = motor
    bpy.ops.rigidbody.constraint_add(type="SLIDER")
    rc = motor.rigid_body_constraint
    rc.object1 = None
    rc.object2 = cart
    rc.use_motor_lin = True
    rc.motor_lin_target_velocity = 1.5
    dt = 1.0 / scene.render.fps
    for f in range(2, 26):
        scene.frame_set(f)
        n = f - 1
        assert float(cart.location[0]) == pytest.approx(
            1.5 * n * dt, abs=1e-12
        )
        assert float(cart.location[1]) == 0.0
        assert float(cart.location[2]) == 1.2


def test_sim_cartpole_free_pendulum_period():
    """The producer-side CartpoleScene obeys the same analytic contract:
    with the motor at zero and the cart at rest, theta integrates the
    free pendulum (cart->pole coupling only), so the hanging period is
    2*pi*sqrt(L/g) within 1.5% at its 60 Hz step."""
    from blendjax.producer.sim import CartpoleScene

    scene = CartpoleScene(seed=0)
    scene.reset()
    psi0 = 0.05
    scene.state = np.array([0.0, 0.0, math.pi + psi0, 0.0])
    scene.motor_velocity = 0.0
    T_analytic = 2 * math.pi * math.sqrt(scene.POLE_LEN / scene.GRAVITY)
    frames = int(5 * T_analytic / scene.DT)
    psis, times = [], []
    for i in range(frames):
        scene.step(i)
        psis.append(float(scene.state[2]) - math.pi)
        times.append((i + 1) * scene.DT)
    psis, times = np.asarray(psis), np.asarray(times)
    up = np.where((psis[:-1] < 0) & (psis[1:] >= 0))[0]
    cross = times[up] + (-psis[up]) / (psis[up + 1] - psis[up]) * scene.DT
    assert len(cross) >= 4
    T = float(np.mean(np.diff(cross)))
    assert abs(T - T_analytic) / T_analytic < 0.015


def test_sim_cartpole_upright_divergence_rate():
    """Uncontrolled upright divergence follows the linearized
    theta(t) = theta0 * cosh(sqrt(g/L) t) within 5% while theta stays
    in the small-angle regime (< 0.2 rad)."""
    from blendjax.producer.sim import CartpoleScene

    scene = CartpoleScene(seed=0)
    scene.reset()
    th0 = 0.01
    scene.state = np.array([0.0, 0.0, th0, 0.0])
    scene.motor_velocity = 0.0
    w = math.sqrt(scene.GRAVITY / scene.POLE_LEN)
    for i in range(240):  # 4 s at 60 Hz
        scene.step(i)
        th = float(scene.state[2])
        if th >= 0.2:
            break
        t = (i + 1) * scene.DT
        expected = th0 * math.cosh(w * t)
        assert abs(th - expected) / expected < 0.05
    assert th >= 0.2  # it did diverge (upright is unstable)
