"""Focused regressions for the fake runtime's math and rigid-body
semantics (blendjax.testing.fake_bpy) — locks in the contracts the
scene tests exercise indirectly: euler/matrix consistency, in-place
location tracking, and frame_set's rewind-vs-reevaluation rule."""

import math

import numpy as np
import pytest

from blendjax.testing import install_fake_bpy, reset_fake_bpy


@pytest.fixture()
def bpy():
    mod = install_fake_bpy(background=True)
    reset_fake_bpy(background=True)
    return mod


def test_euler_matrix_roundtrip(bpy):
    """to_euler('XYZ') inverts Euler.to_matrix3 across the non-gimbal
    range, including through object matrix_world with scale applied."""
    obj = bpy.data.objects.new("Probe")
    bpy.context.collection.objects.link(obj)
    rng = np.random.default_rng(0)
    for _ in range(50):
        e = rng.uniform((-np.pi, -np.pi / 2 + 0.1, -np.pi),
                        (np.pi, np.pi / 2 - 0.1, np.pi))
        obj.rotation_euler = e
        obj.scale = rng.uniform(0.5, 2.0, 3)
        got = obj.matrix_world.to_euler("XYZ")
        np.testing.assert_allclose(list(got), e, atol=1e-9)


def test_matrix_translation_tracks_location(bpy):
    obj = bpy.data.objects.new("Probe")
    bpy.context.collection.objects.link(obj)
    obj.location = (1.0, -2.0, 3.0)
    np.testing.assert_array_equal(
        obj.matrix_world.translation, [1.0, -2.0, 3.0]
    )


def _falling_cube(bpy, z=10.0):
    bpy.ops.rigidbody.world_add()
    bpy.ops.mesh.primitive_plane_add(size=40)
    bpy.ops.rigidbody.object_add(type="PASSIVE")
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, z))
    cube = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    return cube


def test_reevaluation_keeps_velocity_rewind_resets_it(bpy):
    """frame_set(frame_current) is a plain re-evaluation (dynamic state
    survives — the common depsgraph-refresh idiom); seeking backward is
    a rewind (velocities zero, like Blender resuming from the cache)."""
    cube = _falling_cube(bpy)
    scene = bpy.context.scene
    for f in range(2, 12):
        scene.frame_set(f)
    z10 = float(cube.location[2])
    v = scene._vel[id(cube)].copy()
    assert v[2] < 0  # falling

    scene.frame_set(scene.frame_current)  # re-evaluation: state kept
    np.testing.assert_array_equal(scene._vel[id(cube)], v)
    assert float(cube.location[2]) == z10

    scene.frame_set(12)  # continues from the kept velocity
    assert float(cube.location[2]) < z10

    cube.location = (0, 0, 10.0)
    scene.frame_set(1)  # rewind: velocities cleared
    assert id(cube) not in scene._vel
    scene.frame_set(2)
    # first post-rewind step starts from rest: the step RAN (nonzero
    # drop) but from zero velocity (small displacement only)
    assert 0.0 < 10.0 - float(cube.location[2]) < 0.1


def test_location_reference_tracks_hinge_body(bpy):
    """obj.location references stay live through physics (in-place
    mutation contract — a cached Vector tracks the object in Blender)."""
    bpy.ops.rigidbody.world_add()
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 1.0))
    cart = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    bpy.ops.mesh.primitive_cube_add(size=1.0, location=(0, 0, 2.0))
    pole = bpy.context.active_object
    bpy.ops.rigidbody.object_add(type="ACTIVE")
    hinge = bpy.data.objects.new("Hinge")
    hinge.location = (0, 0, 1.5)
    bpy.context.collection.objects.link(hinge)
    bpy.context.view_layer.objects.active = hinge
    bpy.ops.rigidbody.constraint_add(type="HINGE")
    hinge.rigid_body_constraint.object1 = cart
    hinge.rigid_body_constraint.object2 = pole

    pole.rotation_euler[1] = 0.3
    cached = pole.location  # grabbed BEFORE physics runs
    scene = bpy.context.scene
    for f in range(2, 10):
        scene.frame_set(f)
    assert cached is pole.location  # same live array
    assert abs(float(cached[0])) > 1e-3  # pendulum swung; cache tracked


def test_oversized_frame_jump_fails_loudly(bpy):
    """Seeks past the physics step guard raise instead of silently
    truncating the simulated span."""
    _falling_cube(bpy)
    scene = bpy.context.scene
    scene.frame_set(2)
    with pytest.raises(RuntimeError, match="frame jump"):
        scene.frame_set(scene.frame_current + 20_000)


def test_visibility_unaffected_by_default_scene_flag(bpy):
    """install/reset honor default_scene switching in place (prior
    imports keep working; the graph actually swaps)."""
    assert len(bpy.data.objects) == 0
    reset_fake_bpy(default_scene=True)
    assert "Cube" in bpy.data.objects and "Camera" in bpy.data.objects
    assert bpy.context.scene.camera is bpy.data.objects["Camera"]
    reset_fake_bpy(default_scene=False)
    assert len(bpy.data.objects) == 0
