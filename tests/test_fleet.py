"""blendjax.fleet: verdict-driven autoscaling, elastic membership,
remote admission, and the Blender-free synthetic producer tier.

Controller policy arms run clockless over fakes (no sockets, no
subprocesses); membership/drain/respawn run against real spawned
producers — the hermetic versions of the acceptance scenarios in
ISSUE 7 / docs/fleet.md.
"""

import subprocess
import sys
import threading
import time

import numpy as np
import pytest
import zmq

from blendjax.fleet import (
    AdmissionServer,
    FleetController,
    FleetPolicy,
    announce,
    leave,
    synthetic_fleet,
)
from blendjax.launcher.launcher import ProcessLauncher, PythonProducerLauncher
from blendjax.obs.lineage import FrameLineage, lineage
from blendjax.utils.metrics import Metrics, metrics

SLEEPER = "import time; time.sleep(120)"
EXIT7 = "import sys; sys.exit(7)"


@pytest.fixture(autouse=True)
def _clean_registries():
    metrics.reset()
    lineage.reset()
    yield
    metrics.reset()
    lineage.reset()


# -- fakes for the clockless controller fixtures -----------------------------


class FakeLauncher:
    """Duck-types the elastic-membership surface of ProcessLauncher."""

    def __init__(self, n: int = 1):
        self.n = n
        self._retired: set = set()
        self.dead: dict = {}  # index -> exit code
        self.added: list = []
        self.respawned: list = []

    def _addr(self, i):
        return f"tcp://127.0.0.1:{9000 + i}"

    def active_indices(self):
        return [i for i in range(self.n) if i not in self._retired]

    def active_count(self):
        return len(self.active_indices())

    def poll_processes(self):
        return [self.dead.get(i) for i in range(self.n)]

    def add_instance(self, extra_args=None):
        i = self.n
        self.n += 1
        self.added.append((i, extra_args))
        return i, {"DATA": self._addr(i)}

    def retire_instance(self, i, drain=True):
        self._retired.add(i)
        return {"DATA": self._addr(i)}

    def respawn_instance(self, i):
        self.dead.pop(i, None)
        self.respawned.append(i)

    def instance_sockets(self, i):
        return {"DATA": self._addr(i)}


class FakeConnector:
    def __init__(self):
        self.connected: list = []
        self.disconnected: list = []

    def connect(self, addr):
        self.connected.append(addr)

    def disconnect(self, addr):
        self.disconnected.append(addr)


class FakeLineage:
    def __init__(self):
        self.registered: list = []
        self.retired: list = []

    def register(self, btid):
        self.registered.append(btid)

    def retire(self, btid):
        self.retired.append(btid)
        return True


def make_controller(launcher, policy, **kw):
    kw.setdefault("connector", FakeConnector())
    kw.setdefault("lineage", FakeLineage())
    kw.setdefault("registry", Metrics())
    kw.setdefault("respawn_dead", True)
    return FleetController(launcher, policy=policy, **kw)


# -- controller policy arms (clockless fixtures) -----------------------------


def test_scale_up_needs_sustained_verdict_then_respects_cooldown():
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=3, up_after=2,
                        down_after=2, cooldown_s=10.0),
    )
    d = ctrl.tick(verdict="producer-bound", now=0.0)
    assert d["action"] == "hold" and d["up_streak"] == 1  # hysteresis
    d = ctrl.tick(verdict="producer-bound", now=1.0)
    assert d["action"] == "scale_up" and d["added"] == [(1, ln._addr(1))]
    assert ctrl.connector.connected == [ln._addr(1)]
    assert ctrl.lineage.registered == [1]
    # cooldown: the new instance gets time to move the verdict
    for t in (2.0, 3.0, 10.5):
        assert ctrl.tick(verdict="producer-bound", now=t)["action"] == "hold"
    d = ctrl.tick(verdict="producer-bound", now=12.0)
    assert d["action"] == "scale_up" and ln.active_count() == 3
    # at max_instances the verdict can rage on — bounds hold
    for t in (30.0, 31.0, 32.0):
        d = ctrl.tick(verdict="echo-saturated", now=t)
        assert d["action"] == "hold" and d["instances"] == 3
    reg = ctrl.registry.report()["counters"]
    assert reg["fleet.scale_ups"] == 2 and "fleet.scale_downs" not in reg


def test_scale_down_drains_through_grace_before_disconnect():
    ln = FakeLauncher(3)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=3, up_after=1,
                        down_after=2, cooldown_s=0.0, drain_grace_s=2.0),
    )
    assert ctrl.tick(verdict="step-bound", now=0.0)["action"] == "hold"
    d = ctrl.tick(verdict="step-bound", now=1.0)
    assert d["action"] == "scale_down" and d["removed"] == [(2, ln._addr(2))]
    # the producer is retired (drained) but the consumer keeps the
    # address connected through the grace window — the flushed tail is
    # still on the pipe
    assert ln._retired == {2}
    assert ctrl.connector.disconnected == []
    assert ctrl.lineage.retired == []
    ctrl.tick(verdict="balanced", now=1.5)  # inside the grace window
    assert ctrl.connector.disconnected == []
    ctrl.tick(verdict="balanced", now=3.5)  # past now=1.0 + 2.0s grace
    assert ctrl.connector.disconnected == [ln._addr(2)]
    assert ctrl.lineage.retired == [2]
    assert ctrl.registry.report()["counters"]["fleet.scale_downs"] == 1


def test_interleaved_verdicts_reset_streaks():
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=3, up_after=2,
                        down_after=2, cooldown_s=0.0),
    )
    for t, kind in enumerate(
        ["producer-bound", "balanced", "producer-bound", "idle",
         "producer-bound", "feed-bound"]
    ):
        d = ctrl.tick(verdict=kind, now=float(t))
        assert d["action"] == "hold", (kind, d)
    assert ln.active_count() == 1


def test_never_scales_down_while_breaching():
    ln = FakeLauncher(3)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=4, up_after=1,
                        down_after=1, cooldown_s=0.0),
        health=lambda: False,  # SLO watchdog says breached
    )
    for t in range(5):
        d = ctrl.tick(verdict="idle", now=float(t))
        assert d["action"] == "hold" and d["healthy"] is False
    assert ln.active_count() == 3
    # scaling UP stays allowed during a breach (more supply can only help)
    assert ctrl.tick(verdict="producer-bound", now=9.0)["action"] == "scale_up"


def test_respawns_dead_instances_and_tags_breach_window():
    ln = FakeLauncher(2)
    ln.dead[0] = 137
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=2),
        health=lambda: False,
    )
    d = ctrl.tick(verdict="balanced", now=0.0)
    assert d["respawned"] == [0] and ln.respawned == [0]
    ev = [e for e in ctrl.events if e["action"] == "respawn"]
    assert len(ev) == 1
    assert ev[0]["exit_code"] == 137 and ev[0]["during_breach"] is True
    assert ctrl.registry.report()["counters"]["fleet.respawns"] == 1
    # retired slots are never respawn material
    ln.retire_instance(1)
    ln.dead[1] = 1
    assert ctrl.tick(verdict="balanced", now=1.0)["respawned"] == []


def test_event_log_bounded_and_state_snapshot():
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=64, up_after=1,
                        cooldown_s=0.0),
        event_log=4,
    )
    for t in range(8):
        ctrl.tick(verdict="producer-bound", now=float(t))
    assert len(ctrl.events) == 4  # bounded deque, newest kept
    assert len(ctrl.scale_events()) == 4
    st = ctrl.state()
    assert st["instances"] == 9 and st["min"] == 1 and st["max"] == 64
    assert st["ticks"] == 8 and st["verdict"] == "producer-bound"
    assert all(e["action"] == "scale_up" for e in st["events"])


def test_remote_admission_lifecycle_with_drain_grace():
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=2, drain_grace_s=2.0),
    )
    r = ctrl.admit_remote("boxA", "tcp://10.0.0.7:5555", {"rate": 30})
    assert r == {"ok": True}
    assert ctrl.connector.connected == ["tcp://10.0.0.7:5555"]
    assert ctrl.lineage.registered == ["boxA"]
    assert ctrl.state()["instances"] == 2  # launched 1 + remote 1
    # idempotent re-announce (producer retried)
    assert ctrl.admit_remote("boxA", "tcp://10.0.0.7:5555")["already"] is True
    # remote members ride OUTSIDE launcher bounds: never retire targets
    assert ctrl.tick(verdict="idle", now=0.0)["instances"] == 2
    # leave schedules the disconnect after the grace window
    assert ctrl.retire_remote("boxA", now=10.0)["ok"] is True
    assert ctrl.connector.disconnected == []
    ctrl.tick(verdict="balanced", now=11.0)
    assert ctrl.connector.disconnected == []
    ctrl.tick(verdict="balanced", now=12.5)
    assert ctrl.connector.disconnected == ["tcp://10.0.0.7:5555"]
    assert ctrl.lineage.retired == ["boxA"]
    assert ctrl.retire_remote("ghost")["ok"] is False


def test_readmission_with_new_addr_retires_stale_endpoint():
    """A remote producer that crashed and rebound a fresh wildcard
    port re-announces under its stable btid: the OLD endpoint must be
    disconnected (through drain grace) instead of leaking a zombie
    TCP-reconnect forever — and the member's lineage stays registered
    (it never left)."""
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=2, drain_grace_s=2.0),
    )
    assert ctrl.admit_remote("boxA", "tcp://10.0.0.7:5555", now=0.0)["ok"]
    assert ctrl.admit_remote("boxA", "tcp://10.0.0.7:6666", now=1.0)["ok"]
    assert ctrl.remote == {"boxA": "tcp://10.0.0.7:6666"}
    assert ctrl.connector.connected == [
        "tcp://10.0.0.7:5555", "tcp://10.0.0.7:6666"
    ]
    ctrl.tick(verdict="balanced", now=1.5)  # inside the grace window
    assert ctrl.connector.disconnected == []
    ctrl.tick(verdict="balanced", now=3.5)  # past now=1.0 + 2.0s grace
    assert ctrl.connector.disconnected == ["tcp://10.0.0.7:5555"]
    assert ctrl.lineage.retired == []  # addr-only: the member stayed
    assert ctrl.state()["instances"] == 2


def test_admit_remote_rejects_malformed_addr_with_reply():
    """The admission endpoint faces the network: junk must be refused
    in the reply, not queued to explode later on the ingest thread."""
    ctrl = make_controller(FakeLauncher(1), FleetPolicy())
    for bad in ("garbage", "tcp://garbage", "tcp://host:notaport", "://x"):
        r = ctrl.admit_remote("boxA", bad)
        assert r["ok"] is False and "malformed" in r["error"], bad
    assert ctrl.connector.connected == []
    assert ctrl.remote == {}
    # path-style protos have no host:port tail — they stay admissible
    assert ctrl.admit_remote("boxB", "ipc:///tmp/feed.sock")["ok"] is True


def test_readmission_of_same_addr_reissues_connect():
    """An already:true re-announce is a RETRY: when the deferred
    connect failed and rolled back, the producer's next announce must
    re-issue it (idempotent at the channel bookkeeping when alive)."""
    ctrl = make_controller(FakeLauncher(1), FleetPolicy())
    assert ctrl.admit_remote("boxA", "tcp://10.0.0.7:5555")["ok"] is True
    r = ctrl.admit_remote("boxA", "tcp://10.0.0.7:5555")
    assert r["already"] is True
    assert ctrl.connector.connected == ["tcp://10.0.0.7:5555"] * 2


def test_malformed_membership_op_is_skipped_not_fatal():
    """Even when a bad endpoint slips past admission, the deferred
    connect must not kill the iterating ingest thread — the op is
    logged, skipped, and the addr removed from bookkeeping."""
    from blendjax.data.stream import RemoteStream

    stream = RemoteStream([], timeoutms=250)
    stream.connect("garbage")
    assert "garbage" in stream.addresses

    class ExplodingRecv:
        def connect(self, addr):
            raise zmq.ZMQError(zmq.EINVAL)

    stream._apply_membership(ExplodingRecv())  # must not raise
    assert "garbage" not in stream.addresses
    assert not stream._membership_ops


def test_announce_addr_rewrites_wildcard_host_only():
    """A standalone producer bound at a wildcard host must announce a
    routable address (zmq LAST_ENDPOINT keeps the 0.0.0.0 host; a
    remote consumer connecting to it would reach ITSELF)."""
    from blendjax.fleet.synthetic import announce_addr

    assert announce_addr("tcp://127.0.0.1:5555") == "tcp://127.0.0.1:5555"
    assert announce_addr("tcp://10.1.2.3:7777") == "tcp://10.1.2.3:7777"
    rewritten = announce_addr("tcp://0.0.0.0:5555")
    host, _, port = rewritten.partition("://")[2].rpartition(":")
    assert port == "5555" and host not in ("0.0.0.0", "*", "::", "[::]")
    assert rewritten.startswith("tcp://")


def test_controller_thread_lifecycle():
    ln = FakeLauncher(1)
    ctrl = make_controller(
        ln, FleetPolicy(min_instances=1, max_instances=1),
        interval_s=0.02, diagnose=lambda: "balanced",
    )
    with ctrl:
        time.sleep(0.15)
    assert ctrl.state()["ticks"] >= 2
    assert ctrl._thread is None


def test_admission_server_protocol_roundtrip():
    """announce/leave over the real REP endpoint, plus the protocol
    error paths (this socket faces the network: no pickle, no crash on
    a bad request)."""
    log: list = []
    with AdmissionServer(
        on_announce=lambda btid, addr, tele: (
            log.append(("announce", btid, addr, tele)) or {"ok": True}
        ),
        on_leave=lambda btid: log.append(("leave", btid)) or {"ok": True},
    ) as srv:
        assert srv.addr and not srv.addr.endswith(":0")  # wildcard resolved
        r = announce(srv.addr, "boxA", "tcp://1.2.3.4:5", {"rate": 30})
        assert r == {"ok": True}
        assert leave(srv.addr, "boxA")["ok"] is True
        from blendjax.transport.channels import RpcClient

        client = RpcClient(srv.addr, timeoutms=5000, allow_pickle=False)
        try:
            bad = client.call(op="announce")  # missing btid/data_addr
            assert bad["ok"] is False and "btid" in bad["error"]
            assert client.call(op="warp")["ok"] is False
        finally:
            client.close()
    assert log == [
        ("announce", "boxA", "tcp://1.2.3.4:5", {"rate": 30}),
        ("leave", "boxA"),
    ]


# -- lineage register/retire --------------------------------------------------


def _stamped(btid, seq):
    return {"btid": btid, "_seq": seq, "_pub_wall": time.time(),
            "_pub_mono": time.monotonic()}


def test_lineage_retire_makes_btid_reuse_fresh_not_a_restart():
    lin = FrameLineage()
    lin.register(7)
    assert lin.report()["7"]["received"] == 0  # visible before 1st frame
    for s in range(3):
        lin.ingest(_stamped(7, s))
    # same btid, new numbering, NO retire: that's a producer restart
    lin.ingest(_stamped(7, 0))
    assert lin.report()["7"]["restarts"] == 1
    # retire + rejoin: fresh tracking, not a second restart and not a
    # reorder storm
    assert lin.retire(7) is True
    assert lin.retire(7) is False
    assert "7" not in lin.report()
    lin.ingest(_stamped(7, 0))
    rep = lin.report()["7"]
    assert rep["restarts"] == 0 and rep["seq_reorders"] == 0
    assert rep["seq_gaps"] == 0


# -- membership plumbing (no subprocesses) ------------------------------------


class FakeShardStream:
    def __init__(self, addresses):
        self.addresses = list(addresses)

    def connect(self, addr):
        if addr not in self.addresses:
            self.addresses.append(addr)

    def disconnect(self, addr):
        self.addresses.remove(addr)


def test_sharded_ingest_routes_connect_to_least_loaded_shard():
    from blendjax.data.shard_ingest import ShardedHostIngest

    pool = ShardedHostIngest.__new__(ShardedHostIngest)
    pool.streams = [
        FakeShardStream(["tcp://a", "tcp://b"]),
        FakeShardStream(["tcp://c"]),
    ]
    pool.connect("tcp://d")  # least-loaded shard takes the newcomer
    assert pool.streams[1].addresses == ["tcp://c", "tcp://d"]
    pool.connect("tcp://a")  # already a member: no double-connect
    assert pool.streams[0].addresses == ["tcp://a", "tcp://b"]
    pool.disconnect("tcp://d")  # owner-routed
    assert pool.streams[1].addresses == ["tcp://c"]
    pool.disconnect("tcp://ghost")  # unknown: no-op, no raise


def test_pipeline_opaque_source_rejects_membership():
    from blendjax.data import StreamDataPipeline

    pipe = StreamDataPipeline(iter([]), batch_size=2)
    with pytest.raises(RuntimeError, match="runtime membership"):
        pipe.connect("tcp://127.0.0.1:5555")


# -- elastic launcher against real processes ----------------------------------


def test_scale_to_grows_and_shrinks_with_stable_indices():
    with PythonProducerLauncher(
        script="-c", script_args=[SLEEPER], num_instances=1,
        bind_grace_s=0.3,
    ) as ln:
        added, removed = ln.scale_to(3)
        assert [i for i, _ in added] == [1, 2] and removed == []
        assert ln.active_count() == 3
        addrs = ln.launch_info.addresses["DATA"]
        assert len(addrs) == 3 and len(set(addrs)) == 3
        ln.assert_alive()
        added, removed = ln.scale_to(1)
        assert added == [] and [i for i, _ in removed] == [2, 1]
        assert ln.active_indices() == [0] and ln.retired == {1, 2}
        # retired slots: reported dead by poll, never respawned, and
        # invisible to assert_alive
        codes = ln.poll()
        assert codes[1] is not None and codes[2] is not None
        ln.assert_alive()


def test_add_instance_retries_free_port_race_then_succeeds():
    """The satellite fix: a spawn that dies inside the bind grace
    window (the probed-then-closed port was stolen) is retried with
    FRESH addresses instead of failing the scale-up."""
    calls = {"grow": 0}

    def command(i, handshake):
        if i == 0:
            return [sys.executable, "-c", SLEEPER] + handshake
        calls["grow"] += 1
        body = EXIT7 if calls["grow"] == 1 else SLEEPER
        return [sys.executable, "-c", body] + handshake

    with ProcessLauncher(
        command, num_instances=1, named_sockets=["DATA"], bind_grace_s=3.0,
    ) as ln:
        i, sockets = ln.add_instance()
        assert i == 1 and calls["grow"] == 2  # one failure, one retry
        assert ln.active_count() == 2
        assert ln.processes[1].poll() is None
        addrs = ln.launch_info.addresses["DATA"]
        assert len(set(addrs)) == 2 and sockets["DATA"] == addrs[1]


def test_add_instance_inherits_running_fleet_args():
    """extra_args=None must inherit the fleet's per-instance args: a
    scale-up producer with script-default shape/encoding would feed
    the consumer's decoder mismatched frames mid-run."""
    with PythonProducerLauncher(
        script="-c", script_args=[SLEEPER], num_instances=1,
        instance_args=[["--shape", "64", "64"]], bind_grace_s=0.3,
    ) as ln:
        i, _ = ln.add_instance()
        assert ln.instance_args[i] == ["--shape", "64", "64"]
        assert "--shape" in ln.launch_info.commands[i]
        j, _ = ln.add_instance(extra_args=[])  # explicit bare instance
        assert ln.instance_args[j] == []


def test_add_instance_gives_up_after_bounded_retries():
    def command(i, handshake):
        body = SLEEPER if i == 0 else EXIT7
        return [sys.executable, "-c", body] + handshake

    with ProcessLauncher(
        command, num_instances=1, named_sockets=["DATA"], bind_grace_s=3.0,
    ) as ln:
        with pytest.raises(RuntimeError, match="failed to come up"):
            ln.add_instance()
        # the failed growth left no half-added slot behind
        assert ln.active_count() == 1 and ln.num_instances == 1
        assert len(ln.launch_info.addresses["DATA"]) == 1


# -- synthetic producer tier --------------------------------------------------


def _consume(stream, want):
    """Iterate ``want`` frames; returns (frames, seconds from first)."""
    it = iter(stream)
    first = next(it)
    n = first["image"].shape[0]
    t0 = time.monotonic()
    while n < want:
        n += next(it)["image"].shape[0]
    return n, time.monotonic() - t0


def test_synthetic_tier_rate_floor_and_throttle_accuracy():
    # unthrottled: the native rasterizer runs ~1,100 frames/s (PARITY
    # r2); even a loaded CI core clears 250
    with synthetic_fleet(1, frames=1024) as ln:
        stream = _stream_for(ln, 0)
        n, dt = _consume(stream, 1024)
        assert n / dt >= 250.0, f"{n / dt:.0f} img/s"
    # --rate is the knob that makes producer-bound regimes
    # reproducible: an absolute schedule, so jitter can't drift it
    metrics.reset()
    lineage.reset()
    with synthetic_fleet(1, frames=180, rate=60.0) as ln:
        stream = _stream_for(ln, 0)
        n, dt = _consume(stream, 180)
        rate = n / dt
        assert 40.0 <= rate <= 85.0, f"{rate:.0f} img/s at --rate 60"
    assert lineage.total_gaps() == 0


def _stream_for(launcher, *indices, **kw):
    from blendjax.data.stream import RemoteStream

    kw.setdefault("timeoutms", 15000)
    return RemoteStream(
        [launcher.instance_sockets(i)["DATA"] for i in indices], **kw
    )


def test_retire_with_drain_delivers_every_in_flight_frame():
    """Every rendered frame sits in a NEVER-full partial batch (batch
    size larger than what gets rendered): only the SIGTERM drain path
    (finish frame -> ship partial -> flush socket) can deliver them.
    A contiguous frameid prefix proves zero in-flight loss."""
    with synthetic_fleet(
        1, shape=(16, 16), batch=4096, rate=200.0,
    ) as ln:
        stream = _stream_for(ln, 0, timeoutms=10000)
        got: list = []
        # iterate on a thread: RemoteStream's generator only connects
        # once iteration starts, and the ONLY message here is the
        # drained partial at retirement
        consumer = threading.Thread(
            target=lambda: got.append(next(iter(stream))), daemon=True
        )
        consumer.start()
        time.sleep(1.5)  # ~200-300 frames rendered into the open batch
        ln.retire_instance(0, drain=True)
        consumer.join(timeout=10)
        assert got, "drained partial batch never reached the consumer"
        ids = list(np.asarray(got[0]["frameid"]).ravel())
        assert len(ids) >= 20
        assert ids == list(range(1, len(ids) + 1))
        stream.request_stop()


def test_retire_without_drain_loses_the_open_batch():
    """The contrast leg: SIGKILL (drain=False) never runs the flush, so
    the open partial batch dies with the process — the measured reason
    retire-with-drain is the default."""
    with synthetic_fleet(
        1, shape=(16, 16), batch=4096, rate=200.0,
    ) as ln:
        stream = _stream_for(ln, 0, timeoutms=1500)
        got: list = []

        def consume():
            try:
                got.append(next(iter(stream)))
            except Exception:
                pass  # receive timeout: nothing was ever delivered

        consumer = threading.Thread(target=consume, daemon=True)
        consumer.start()
        time.sleep(1.0)
        ln.retire_instance(0, drain=False)
        consumer.join(timeout=10)
        assert got == []
        stream.request_stop()


# -- live end-to-end: scale-up, admission, kill -> respawn -> recovery --------


def test_scale_up_under_producer_bound_raises_img_s_without_gaps():
    """The acceptance loop, hermetically: a throttled synthetic fleet
    pins the supply; a sustained producer-bound verdict makes the
    controller add an instance; the consumer admits it MID-RUN; the
    measured rate rises by roughly the known per-instance increment and
    lineage counts zero gaps across the membership change."""
    args = ["--shape", "32", "32", "--batch", "4", "--rate", "40"]
    with synthetic_fleet(
        1, shape=(32, 32), batch=4, rate=40.0, bind_grace_s=0.5,
    ) as ln:
        stream = _stream_for(ln, 0)
        it = iter(stream)
        next(it)  # producer is up

        def rate_over(seconds):
            n = 0
            t0 = time.monotonic()
            while time.monotonic() - t0 < seconds:
                n += next(it)["image"].shape[0]
            return n / (time.monotonic() - t0)

        r1 = rate_over(1.5)
        ctrl = FleetController(
            ln, connector=stream,
            policy=FleetPolicy(min_instances=1, max_instances=2,
                               up_after=2, cooldown_s=0.0),
            respawn_dead=False, instance_args=args,
        )
        assert ctrl.tick(verdict="producer-bound")["action"] == "hold"
        d = ctrl.tick(verdict="producer-bound")
        assert d["action"] == "scale_up" and d["instances"] == 2
        rate_over(2.5)  # discard: instance 1 is still booting
        r2 = rate_over(2.0)
        assert r2 >= 1.45 * r1, f"{r1:.0f} -> {r2:.0f} img/s"
        assert r2 <= 2.8 * r1, f"{r1:.0f} -> {r2:.0f} img/s (throttle?)"
        rep = lineage.report()
        assert set(rep) >= {"0", "1"}, rep.keys()
        assert all(v["seq_gaps"] == 0 for v in rep.values()), rep
        assert metrics.report()["counters"].get("wire.seq_gaps", 0) == 0
        assert [e["action"] for e in ctrl.scale_events()] == ["scale_up"]
        stream.request_stop()


def test_remote_producer_announces_streams_and_leaves_cleanly():
    """Pillar 3 end-to-end: a standalone producer (another process,
    its own bound socket — the render-box topology) announces itself to
    the consumer's admission endpoint, is connected into a LIVE
    iteration, streams its frames gap-free, and leaves through the
    drain grace window."""
    from blendjax.data.stream import RemoteStream

    stream = RemoteStream([], timeoutms=250, on_timeout=lambda: True)
    ctrl = FleetController(
        FakeLauncher(0), connector=stream,
        policy=FleetPolicy(min_instances=1, max_instances=1,
                           drain_grace_s=0.5),
        respawn_dead=False,
    )
    with AdmissionServer(
        on_announce=ctrl.admit_remote, on_leave=ctrl.retire_remote,
    ) as srv:
        proc = subprocess.Popen([
            sys.executable, "-m", "blendjax.fleet.synthetic",
            "--bind", "tcp://127.0.0.1:0", "--btid", "render-box-7",
            "--announce", srv.addr, "--shape", "32", "32",
            "--batch", "8", "--frames", "120",
        ])
        try:
            it = iter(stream)
            n = 0
            deadline = time.monotonic() + 30
            while n < 120 and time.monotonic() < deadline:
                n += next(it)["image"].shape[0]
            assert n == 120
            # leave() is an RPC the producer makes AFTER its final
            # flush — give it a moment to land
            deadline = time.monotonic() + 10
            while ctrl.remote and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ctrl.remote == {}
            ev = [e["action"] for e in ctrl.events]
            assert ev[:2] == ["admit", "leave"]
            # flush the scheduled disconnect once the grace passed
            time.sleep(0.6)
            ctrl.tick(verdict="balanced")
            assert [e["action"] for e in ctrl.events][-1] == "disconnect"
            rep = lineage.report()
            assert "render-box-7" not in rep  # retired from lineage
            assert metrics.report()["counters"].get("wire.seq_gaps", 0) == 0
            assert proc.wait(timeout=15) == 0
        finally:
            stream.request_stop()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=5)


def test_kill_breach_respawn_recovery_healthz_roundtrip(tmp_path):
    """The watchdog loop closed: kill a producer mid-run -> the SLO
    breaches (/healthz 503) -> the controller respawns the instance in
    place -> flow resumes -> the SLO recovers (/healthz 200). Lineage
    reads the fresh numbering as one producer restart, not a drop
    storm."""
    import json
    import urllib.error
    import urllib.request

    from blendjax.data.batcher import HostIngest
    from blendjax.obs import StatsReporter, start_http_exporter

    def get_status(url):
        try:
            with urllib.request.urlopen(url, timeout=5) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    with synthetic_fleet(1, shape=(32, 32), batch=4, rate=60.0) as ln:
        stream = _stream_for(ln, 0, timeoutms=250, on_timeout=lambda: True)
        ingest = HostIngest(stream, batch_size=4, prefetch=2).start()
        stop = threading.Event()

        def drain():
            for _ in ingest:
                if stop.is_set():
                    break

        drainer = threading.Thread(target=drain, daemon=True)
        rep = StatsReporter(
            interval_s=3600, slos=["rate(ingest.items) >= 3"],
        )
        ctrl = FleetController(
            ln, connector=stream, diagnose=lambda: "balanced",
            health=lambda: rep.healthy,
            policy=FleetPolicy(min_instances=1, max_instances=1),
        )
        srv = start_http_exporter(port=0, health=rep.health)
        url = f"http://127.0.0.1:{srv.port}/healthz"
        try:
            drainer.start()
            # producer boot takes ~1s: wait for the first frames
            deadline = time.monotonic() + 15
            while (
                time.monotonic() < deadline
                and not metrics.report()["counters"].get("ingest.items")
            ):
                time.sleep(0.1)
            assert metrics.report()["counters"].get("ingest.items")
            rep.tick()  # baseline tick: rates have no evidence yet
            time.sleep(0.5)
            rep.tick()  # live flow, healthy
            assert rep.healthy, rep.watchdog.state()
            assert get_status(url)[0] == 200
            proc = ln.processes[0]
            proc.kill()
            proc.wait(timeout=5)
            time.sleep(0.5)  # stragglers drain off the zmq pipe
            rep.tick()  # window may still hold the pre-kill tail
            time.sleep(1.2)  # one fully dry window
            rep.tick()
            assert not rep.healthy, rep.watchdog.state()
            assert get_status(url)[0] == 503
            d = ctrl.tick()  # liveness pass finds the corpse
            assert d["respawned"] == [0]
            ev = [e for e in ctrl.events if e["action"] == "respawn"]
            assert ev[0]["during_breach"] is True
            assert (
                metrics.report()["counters"]["fleet.respawns"] == 1
            )
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and not rep.healthy:
                time.sleep(0.7)
                rep.tick()
            assert rep.healthy, rep.watchdog.state()
            assert get_status(url)[0] == 200
            # one restart, zero phantom drops from the respawn
            rep0 = lineage.report()["0"]
            assert rep0["restarts"] == 1 and rep0["seq_gaps"] == 0
        finally:
            stop.set()
            stream.request_stop()
            srv.close()
            try:
                ingest.stop(timeout=10)
            except Exception:
                pass
