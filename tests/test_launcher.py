"""Launcher tests against real spawned producer processes.

Reference model: ``tests/test_launcher.py`` (arg/seed/socket plumbing,
multi-machine via a second process, liveness). Uses the headless fake
producer instead of Blender.
"""

import multiprocessing as mp
import os
import sys
import time

import pytest

from blendjax.launcher import LaunchInfo, parse_launch_args
from blendjax.launcher.arguments import format_launch_args
from blendjax.launcher.launcher import PythonProducerLauncher
from blendjax.transport import DataReceiverSocket

PRODUCER = os.path.join(os.path.dirname(__file__), "producers", "echo_producer.py")


def test_arguments_roundtrip():
    argv = ["ignored", "stuff", "--"] + format_launch_args(
        3, 13, {"DATA": "tcp://127.0.0.1:11000", "CTRL": "tcp://127.0.0.1:11004"},
        extra=["--render-every", "10"],
    )
    args, remainder = parse_launch_args(argv)
    assert args.btid == 3 and args.btseed == 13
    assert args.btsockets == {
        "DATA": "tcp://127.0.0.1:11000",
        "CTRL": "tcp://127.0.0.1:11004",
    }
    assert remainder == ["--render-every", "10"]
    # alias properties
    assert args.instance_id == 3 and args.seed == 13 and args.sockets


def test_launch_two_instances_handshake():
    """Two instances get distinct ids, seeds seed+i, distinct tcp addresses,
    and their per-instance extra args (reference ``test_launcher.py:20-44``)."""
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA"],
        seed=10,
        instance_args=[["--x", "a"], ["--x", "b"]],
    ) as launcher:
        addrs = launcher.addresses["DATA"]
        assert len(addrs) == 2 and len(set(addrs)) == 2
        assert all(a.startswith("tcp://127.0.0.1:") for a in addrs)
        recv = DataReceiverSocket(addrs, timeoutms=10000)
        seen = {}
        while len(seen) < 2:
            msg, _ = recv.recv()
            seen[msg["btid"]] = msg
        recv.close()
    assert seen[0]["btseed"] == 10 and seen[1]["btseed"] == 11
    assert seen[0]["remainder"] == ["--x", "a"]
    assert seen[1]["remainder"] == ["--x", "b"]
    assert seen[0]["sockets"]["DATA"] == addrs[0]


def test_assert_alive_and_teardown():
    with PythonProducerLauncher(script=PRODUCER, num_instances=1) as launcher:
        launcher.assert_alive()
        pid = launcher.processes[0].pid
    # context exit must have terminated the producer
    with pytest.raises(OSError):
        os.kill(pid, 0)


def test_dead_producer_detected():
    with PythonProducerLauncher(
        script="-c", script_args=["import sys; sys.exit(3)"], num_instances=1
    ) as launcher:
        # -c trick: argv becomes [python, -c, 'exit(3)', --, handshake...]
        # Interpreter startup can take a couple of seconds on small hosts.
        launcher.processes[0].wait(timeout=30)
        with pytest.raises(RuntimeError, match="died"):
            launcher.assert_alive()


def test_respawn_brings_producer_back():
    with PythonProducerLauncher(
        script=PRODUCER, num_instances=1, respawn=True
    ) as launcher:
        first = launcher.processes[0]
        first.terminate()
        first.wait()
        launcher.poll()
        launcher.assert_alive()
        assert launcher.processes[0].pid != first.pid


def _remote_launch(info_path, ready):
    from blendjax.launcher.launcher import PythonProducerLauncher

    with PythonProducerLauncher(script=PRODUCER, num_instances=1, seed=5) as ln:
        ln.launch_info.save_json(info_path)
        ready.set()
        ln.wait()


def test_two_machine_workflow_via_launch_info(tmp_path):
    """Launch in another process, connect via serialized LaunchInfo
    (reference ``test_launcher.py:47-91`` / ``apps/launch.py``)."""
    info_path = str(tmp_path / "launch_info.json")
    ready = mp.Event()
    proc = mp.Process(target=_remote_launch, args=(info_path, ready))
    proc.start()
    try:
        assert ready.wait(timeout=30)
        info = LaunchInfo.load_json(info_path)
        recv = DataReceiverSocket(info.addresses["DATA"], timeoutms=10000)
        msg, _ = recv.recv()
        assert msg["btid"] == 0 and msg["btseed"] == 5
        recv.close()
    finally:
        proc.terminate()
        proc.join(timeout=10)


def test_launch_info_roundtrip(tmp_path):
    info = LaunchInfo(
        addresses={"DATA": ["tcp://1.2.3.4:11000"]},
        commands=["blender ..."],
        processes=[123],
    )
    p = tmp_path / "li.json"
    info.save_json(str(p))
    back = LaunchInfo.load_json(str(p))
    assert back == info
    # file-object path (the reference's nullcontext bug regression test)
    import io

    buf = io.StringIO()
    info.save_json(buf)
    assert LaunchInfo.from_json(buf.getvalue()) == info


def test_cli_app_python_kind(tmp_path):
    """blendjax-launch with a python-producer config writes LaunchInfo."""
    import json
    import subprocess

    cfg = {
        "kind": "python",
        "script": PRODUCER,
        "num_instances": 1,
        "named_sockets": ["DATA"],
        "seed": 2,
    }
    cfg_path = tmp_path / "launch.json"
    cfg_path.write_text(json.dumps(cfg))
    out_path = tmp_path / "info.json"
    proc = subprocess.Popen(
        [sys.executable, "-m", "blendjax.launcher.apps", str(cfg_path),
         "--out", str(out_path)],
        cwd=str(tmp_path),
    )
    try:
        deadline = time.time() + 30
        while not out_path.exists() and time.time() < deadline:
            time.sleep(0.1)
        assert out_path.exists()
        info = LaunchInfo.load_json(str(out_path))
        recv = DataReceiverSocket(info.addresses["DATA"], timeoutms=10000)
        msg, _ = recv.recv()
        assert msg["btseed"] == 2
        recv.close()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


@pytest.mark.skipif(sys.platform != "linux", reason="PDEATHSIG is Linux-only")
def test_producers_die_with_killed_launcher(tmp_path):
    """Orphan-proofing: SIGKILL the launcher process (its __exit__ never
    runs) and the kernel's parent-death signal must still reap the
    producer — a leaked producer loops forever and starves shared-core
    hosts."""
    import json
    import signal
    import subprocess
    import textwrap

    # The cube producer runs FOREVER without --frames, so the assertion
    # cannot pass vacuously by the producer exiting on its own (the echo
    # producer self-exits after ~10s, inside the polling window).
    forever = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    child_src = textwrap.dedent(
        """
        import json, os, time
        from blendjax.launcher import PythonProducerLauncher
        ln = PythonProducerLauncher(
            script=%r, num_instances=1, named_sockets=["DATA"], seed=0,
            instance_args=[["--shape", "32", "32"]],
        ).__enter__()
        print(json.dumps(ln.launch_info.processes), flush=True)
        time.sleep(60)  # parent SIGKILLs us; producer must die anyway
        """
        % forever
    )
    p = subprocess.Popen(
        [sys.executable, "-c", child_src], stdout=subprocess.PIPE, text=True
    )
    try:
        pids = json.loads(p.stdout.readline())
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=10)
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                os.kill(pids[0], 0)
            except ProcessLookupError:
                return  # reaped
            time.sleep(0.2)
        os.kill(pids[0], signal.SIGKILL)  # clean up before failing
        pytest.fail("producer outlived its SIGKILLed launcher")
    finally:
        if p.poll() is None:
            p.kill()


def test_wait_does_not_hold_the_membership_lock():
    """BJX117/BJX119 regression: wait() snapshots under the lock but
    blocks OUTSIDE it, so a fleet controller can still poll/scale while
    the owner waits for the fleet to exit."""
    import sys as _sys
    import threading

    from blendjax.launcher import ProcessLauncher

    def command(i, handshake):
        return [_sys.executable, "-c", "import time; time.sleep(30)"] + handshake

    with ProcessLauncher(command, num_instances=1,
                         named_sockets=["DATA"]) as ln:
        done = threading.Event()
        codes = []

        def waiter():
            codes.append(ln.wait())
            done.set()

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        # while wait() blocks on the child, the membership surface must
        # stay available (pre-fix this deadlocked until the child died)
        for _ in range(5):
            assert ln.poll_processes() == [None]
            assert ln.active_indices() == [0]
        ln.retire_instance(0, drain=False)
        assert done.wait(10.0), "wait() never returned after the kill"
        assert codes and codes[0][0] is not None
