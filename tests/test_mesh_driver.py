"""MeshTrainDriver: the live pipeline on a named mesh.

The load-bearing contract (ISSUE 8 / ROADMAP item 1): sharding is a
LAYOUT choice, never a math change — the same recorded stream through
``MeshTrainDriver`` on a 1-device and an 8-device CPU mesh produces
identical f32 losses (within the repo's established equivalence
tolerance: collective reduction reorders shift the last float32 bits,
wrong sharding math is orders of magnitude away — see
``blendjax.testing.equivalence``), with the one-dispatch-per-step and
donation invariants intact, and exact fresh/echoed accounting when the
echo reservoir rides along.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from blendjax.data import StreamDataPipeline
from blendjax.data.echo import EchoingPipeline, SampleReservoir
from blendjax.models import CubeRegressor
from blendjax.parallel import (
    batch_sharding,
    create_mesh,
    ring_sharding,
)
from blendjax.train import MeshTrainDriver
from blendjax.utils.metrics import metrics as reg

# last-bits-of-f32 on a ~1e-1 loss: the same bar family the dryrun's
# equivalence gates use (reduction reorder moves ~1e-7; wrong sharding
# math moves orders of magnitude)
F32_EXACT_ATOL = 5e-6

B = 16
HW = 32


def _mesh(n):
    return create_mesh({"data": n}, devices=jax.devices()[:n])


def _messages(n=12, batch=B, seed=0):
    """A deterministic recorded stream: the SAME message sequence every
    call, so two mesh legs consume identical bytes."""
    rng = np.random.default_rng(seed)
    for _ in range(n):
        yield {
            "_prebatched": True,
            "btid": 0,
            "image": rng.integers(0, 255, (batch, HW, HW, 4), np.uint8),
            "xy": (rng.random((batch, 8, 2)) * HW).astype(np.float32),
        }


def _model():
    return CubeRegressor(features=(8, 16), dtype=jnp.float32)


def _drive(n_dev, n_msgs=10, **driver_kwargs):
    mesh = _mesh(n_dev)
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
        sync_every=1, inflight=2, **driver_kwargs,
    )
    with StreamDataPipeline(
        _messages(n_msgs), batch_size=B, mesh=mesh
    ) as pipe:
        for sb in pipe:
            drv.submit(sb)
    drv.finish()
    return drv


def test_sharded_vs_single_device_losses_identical():
    """The acceptance gate: same recorded stream, 1-device vs 8-device
    mesh, f32 losses equal step for step."""
    l1 = np.asarray(_drive(1).losses)
    l8 = np.asarray(_drive(8).losses)
    assert l1.shape == l8.shape and len(l1) == 10
    np.testing.assert_allclose(l1, l8, rtol=0, atol=F32_EXACT_ATOL)


def test_mesh_batches_actually_shard_over_data():
    mesh = _mesh(8)
    with StreamDataPipeline(
        _messages(2), batch_size=B, mesh=mesh
    ) as pipe:
        sb = next(iter(pipe))
    assert len(sb["image"].sharding.device_set) == 8
    # every chip holds an equal B/8 slice of the batch
    shard_shapes = {
        s.data.shape for s in sb["image"].addressable_shards
    }
    assert shard_shapes == {(B // 8, HW, HW, 4)}


def test_one_dispatch_per_step_under_sharding():
    reg.reset()
    drv = _drive(8, n_msgs=6)
    spans = reg.report()["spans"]
    assert spans.get("decode.dispatch", {}).get("count", 0) == 0
    assert spans["train.dispatch"]["count"] == drv.steps == 6
    assert drv.dispatches == drv.steps


def test_mesh_step_donation_keeps_state_buffers_stable():
    """Pinned out_shardings + donation: the param buffers never move
    across steps (per-shard pointer equality), so the optimizer state
    is updated in place on every chip."""
    mesh = _mesh(8)
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
        sync_every=0, inflight=1,
    )
    batches = iter(
        StreamDataPipeline(_messages(4), batch_size=B, mesh=mesh)
    )
    drv.submit(next(batches))
    drv.drain()
    leaf = jax.tree_util.tree_leaves(drv.state.params)[0]
    ptrs0 = [
        s.data.unsafe_buffer_pointer() for s in leaf.addressable_shards
    ]
    for sb in batches:
        drv.submit(sb)
    drv.drain()
    leaf = jax.tree_util.tree_leaves(drv.state.params)[0]
    ptrs1 = [
        s.data.unsafe_buffer_pointer() for s in leaf.addressable_shards
    ]
    assert ptrs0 == ptrs1


def test_batch_size_must_divide_mesh_axis():
    with pytest.raises(ValueError, match="divide evenly"):
        StreamDataPipeline(_messages(1), batch_size=12, mesh=_mesh(8))


def test_partial_tail_pads_to_mesh_divisible_bucket():
    """A ragged final batch smaller than the shard count must still
    place: the pad stage restricts its bucket ladder to multiples of
    the batch axis's shard count, so a 3-row tail on an 8-way mesh
    pads to 8 rows + mask instead of crashing device_put."""

    def frames(n=35):
        rng = np.random.default_rng(1)
        for i in range(n):
            yield {
                "btid": 0, "frameid": i,
                "image": rng.integers(0, 255, (HW, HW, 4), np.uint8),
                "xy": (rng.random((8, 2)) * HW).astype(np.float32),
            }

    mesh = _mesh(8)
    with StreamDataPipeline(
        frames(), batch_size=B, mesh=mesh, emit_partial_final=True
    ) as pipe:
        batches = list(pipe)
    assert [int(b["image"].shape[0]) for b in batches] == [B, B, 8]
    tail = batches[-1]
    assert "_mask" in tail and float(np.asarray(tail["_mask"]).sum()) == 3
    assert len(tail["image"].sharding.device_set) == 8


def test_feeder_places_each_batch_in_one_call(monkeypatch):
    """The placement contract BJX111 lints for: ONE grouped device_put
    per batch on a single-host mesh, never a per-field (or worse,
    per-device) loop."""
    from blendjax.data.pipeline import DeviceFeeder

    mesh = _mesh(8)
    feeder = DeviceFeeder(mesh=mesh)
    calls = []
    real = jax.device_put

    def counting(x, *a, **k):
        calls.append(x)
        return real(x, *a, **k)

    monkeypatch.setattr(jax, "device_put", counting)
    placed = feeder._place({
        "image": np.zeros((B, HW, HW, 4), np.uint8),
        "xy": np.zeros((B, 8, 2), np.float32),
        "weights": np.zeros((B,), np.float32),
        "_meta": [{"btid": 0}],
        "btid": 1,
    })
    assert len(calls) == 1
    assert set(placed) == {"image", "xy", "weights", "_meta", "btid"}
    assert len(placed["image"].sharding.device_set) == 8


def test_mfu_scales_by_participating_chips():
    mesh = _mesh(8)
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
        flops_per_image=1e6, peak_flops_per_chip=1e12,
    )
    assert drv.chips == 8
    assert drv.peak_flops == pytest.approx(8e12)
    stats = drv.stats
    assert stats["chips"] == 8 and stats["processes"] == 1


# -- the fused packed path on a mesh ------------------------------------------


def _tile_messages(n=6, batch=8):
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
        TileDeltaEncoder,
        pack_batch,
    )

    rng = np.random.default_rng(3)
    ref = rng.integers(0, 255, (HW, HW, 4), np.uint8)
    enc = TileDeltaEncoder(ref, tile=(16, 32))
    for k in range(n):
        frames = []
        for i in range(batch):
            img = ref.copy()
            img[8:16, 8:16] = (7 + 13 * i + 29 * k) % 251
            frames.append(img)
        deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
        idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
        msg = {
            "_prebatched": True, "btid": 0,
            "image" + TILEIDX_SUFFIX: idx,
            "image" + TILES_SUFFIX: tiles,
            "image" + TILESHAPE_SUFFIX: [HW, HW, 4, 16, 32],
            "xy": (np.random.default_rng(k).random((batch, 8, 2)) * HW
                   ).astype(np.float32),
        }
        if k == 0:
            msg["image" + TILEREF_SUFFIX] = ref
        yield msg


def _drive_fused(n_dev, batch=8, chunk=2, n_msgs=6):
    mesh = _mesh(n_dev)
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((batch, HW, HW, 4), np.uint8),
        fused=True, sync_every=1, inflight=2,
    )
    with StreamDataPipeline(
        _tile_messages(n_msgs, batch), batch_size=batch, mesh=mesh,
        chunk=chunk, emit_packed=True,
    ) as pipe:
        for sb in pipe:
            drv.submit(sb)
    drv.finish()
    return drv


def test_fused_mesh_step_one_dispatch_and_loss_equivalence():
    """The docs' headline fused=True path, pinned: still-encoded packed
    tile groups decode INSIDE the train jit on the mesh — ZERO
    standalone decode dispatches, one device call per chunk group —
    and the in-jit re-shard over `data` changes layout, not math
    (1-device vs 8-device losses f32-equal)."""
    reg.reset()
    d1 = _drive_fused(1)
    spans1 = reg.report()["spans"]
    reg.reset()
    d8 = _drive_fused(8)
    spans8 = reg.report()["spans"]
    for spans, drv in ((spans1, d1), (spans8, d8)):
        assert spans.get("decode.dispatch", {}).get("count", 0) == 0
        assert spans["train.dispatch"]["count"] == drv.dispatches == 3
    l1 = np.concatenate([np.ravel(x) for x in d1.losses])
    l8 = np.concatenate([np.ravel(x) for x in d8.losses])
    np.testing.assert_allclose(l1, l8, rtol=0, atol=F32_EXACT_ATOL)


def test_fused_mesh_step_rejects_missing_data_axis():
    from blendjax.train import make_mesh_fused_step, make_train_state

    mesh = _mesh(8)
    state = make_train_state(
        _model(), np.zeros((8, HW, HW, 4), np.uint8), mesh=mesh
    )
    with pytest.raises(ValueError, match="not an axis"):
        make_mesh_fused_step(state, mesh, data_axis="dp")


# -- the echo reservoir under sharding ----------------------------------------


def test_sharded_reservoir_donation_and_layout():
    mesh = _mesh(8)
    res = SampleReservoir(64, augment=None, sharding=ring_sharding(mesh))
    batch = {
        "image": np.ones((B, 8, 8, 4), np.uint8),
        "xy": np.zeros((B, 8, 2), np.float32),
    }
    res.insert(batch)
    ring = res._buffers["image"]
    assert len(ring.sharding.device_set) == 8
    ptrs0 = [
        s.data.unsafe_buffer_pointer() for s in ring.addressable_shards
    ]
    for _ in range(6):
        res.insert(batch)
    ptrs1 = [
        s.data.unsafe_buffer_pointer()
        for s in res._buffers["image"].addressable_shards
    ]
    assert ptrs0 == ptrs1  # donated scatter: stable sharded buffers
    out = res.sample(np.arange(B))
    # drawn batches leave pre-sharded in the batch layout
    assert out["image"].sharding == batch_sharding(mesh)
    assert out["image"].shape == (B, 8, 8, 4)


def test_sharded_reservoir_capacity_must_divide():
    mesh = _mesh(8)
    with pytest.raises(ValueError, match="divide evenly"):
        SampleReservoir(30, sharding=ring_sharding(mesh))


def _echo_leg(n_dev, n_msgs=6, factor=4):
    """One EchoingPipeline run to exhaustion on a mesh: N*B samples,
    echo factor F, capacity >= all samples, N*B*F divisible by B — so
    every sample is drawn exactly F times and the aggregate accounting
    is deterministic regardless of drain-thread timing."""
    mesh = _mesh(n_dev)
    inner = StreamDataPipeline(
        _messages(n_msgs), batch_size=B, mesh=mesh
    )
    echo = EchoingPipeline(
        inner, capacity=n_msgs * B, max_echo_factor=factor,
        augment=None, mesh=mesh, batch_size=B,
    )
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
        sync_every=1, inflight=2,
    )
    with echo:
        for sb in echo:
            drv.submit(sb)
    drv.finish()
    return echo, drv


def test_echo_accounting_exact_on_mesh_and_matches_single_device():
    """Exact fresh/echoed accounting under sharding: run to stream
    exhaustion with capacity >= every sample — each of the N*B samples
    is drawn exactly ``factor`` times, so fresh == inserted and
    fresh + echoed == steps * B EXACTLY, on both mesh sizes."""
    n_msgs, factor = 6, 4
    e1, _ = _echo_leg(1, n_msgs, factor)
    e8, d8 = _echo_leg(8, n_msgs, factor)
    for e in (e1, e8):
        assert e.inserted == n_msgs * B
        assert e.fresh == e.inserted  # every sample first-used
        assert e.fresh + e.echoed == e.steps * B  # exact, per draw
        assert e.steps == n_msgs * factor  # full budget drained
    assert (e1.steps, e1.fresh, e1.echoed) == (e8.steps, e8.fresh, e8.echoed)
    # the driver trained one dispatch per echoed step on the mesh
    assert d8.dispatches == e8.steps


def test_scripted_reservoir_draws_match_across_meshes():
    """Deterministic reservoir script (no drain thread): same inserts,
    same host-chosen draw indices, same seed — the sharded gather +
    mesh step must produce f32-identical losses on 1 and 8 devices."""

    def leg(n_dev):
        mesh = _mesh(n_dev)
        res = SampleReservoir(
            64, augment=None, rng=7,
            sharding=ring_sharding(mesh) if n_dev > 1 else None,
        )
        drv = MeshTrainDriver.build(
            _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
            sync_every=1, inflight=1,
        )
        idx_rng = np.random.default_rng(11)
        for hb in _messages(4):
            res.insert({"image": hb["image"], "xy": hb["xy"]})
            for _ in range(2):  # echo factor 2 via scripted draws
                idx = idx_rng.integers(0, res.size, B)
                drv.submit(res.sample(idx))
        drv.finish()
        return np.asarray(drv.losses)

    l1, l8 = leg(1), leg(8)
    np.testing.assert_allclose(l1, l8, rtol=0, atol=F32_EXACT_ATOL)


# -- fleet observability -------------------------------------------------------


def test_process_snapshot_is_tagged_and_gathers_locally():
    from blendjax.obs.fleetview import (
        gather_fleet_snapshots,
        process_snapshot,
    )

    reg.reset()
    snap = process_snapshot(driver={"host_blocks": 0})
    assert snap["process"] == 0 and snap["processes"] == 1
    assert snap["verdict"].startswith("doctor:")
    snaps = gather_fleet_snapshots(driver={"host_blocks": 0})
    assert len(snaps) == 1 and snaps[0]["process"] == 0


def test_fleet_report_aggregates_processes():
    from blendjax.obs.fleetview import fleet_report

    snaps = [
        {
            "process": 0, "processes": 2, "seq_gaps": 1,
            "lineage": {"7": {"received": 10}},
            "trace": {"completed": 3, "unordered": 0},
            "verdict": "doctor: producer-bound — starving (spawn more)",
        },
        {
            "process": 1, "processes": 2, "seq_gaps": 2,
            "lineage": {"7": {"received": 4}},
            "trace": {"completed": 2, "unordered": 1},
            "verdict": "doctor: balanced — no single stage dominates",
        },
    ]
    rep = fleet_report(snaps)
    assert rep["processes"] == 2
    assert rep["seq_gaps"] == 3
    assert rep["trace_completed"] == 5 and rep["trace_unordered"] == 1
    # same btid on two processes stays namespaced, never merged
    assert set(rep["lineage"]) == {"p0/7", "p1/7"}
    assert rep["verdicts"]["p0"].startswith("doctor: producer-bound")
    # the actionable verdict wins the dominant pick over 'balanced'
    assert rep["dominant_verdict"] == "producer-bound"


def test_echo_batch_size_must_divide_mesh_axis():
    """Build-time, not first-draw-time: an EchoingPipeline whose drawn
    batches can't split over the mesh raises a named error instead of
    an opaque XLA shard-divisibility failure inside the draw jit."""
    mesh = _mesh(8)
    inner = StreamDataPipeline(_messages(1, batch=12), batch_size=12)
    with pytest.raises(ValueError, match="divide evenly"):
        EchoingPipeline(inner, capacity=16, mesh=mesh, batch_size=12)


# -- layouts: fsdp/tp legs through the driver ---------------------------------

# cross-layout reordering is wider than same-layout (resharding moves
# the all-gather boundaries, so f32 reductions associate differently)
# but still last-bits scale; a wrong program differs in the first
# decimal
CROSS_LAYOUT_ATOL = 5e-5


def _drive_layout(layout, n_msgs=10):
    from blendjax.parallel import resolve_layout

    mesh = resolve_layout(layout).create_mesh()
    drv = MeshTrainDriver.build(
        _model(), mesh, np.zeros((B, HW, HW, 4), np.uint8),
        layout=layout, sync_every=1, inflight=2,
    )
    with StreamDataPipeline(
        _messages(n_msgs), batch_size=B, mesh=mesh
    ) as pipe:
        for sb in pipe:
            drv.submit(sb)
    drv.finish()
    return drv


def test_cross_layout_losses_identical():
    """The tentpole acceptance gate: the SAME recorded stream under
    pure data, data×fsdp, and data×tp layouts trains f32-identically —
    sharding the state is a layout choice, never a math change."""
    base = np.asarray(_drive(8).losses)
    for layout, axis in (("data2xfsdp4", "fsdp"), ("data4xtp2", "tp")):
        drv = _drive_layout(layout)
        losses = np.asarray(drv.losses)
        assert losses.shape == base.shape
        np.testing.assert_allclose(
            base, losses, rtol=0, atol=CROSS_LAYOUT_ATOL
        )
        # and the layout actually sharded the state over its model axis
        specs = [
            tuple(p.sharding.spec)
            for p in jax.tree_util.tree_leaves(drv.state.params)
        ]
        assert any(
            axis in jax.tree_util.tree_leaves(s) for s in specs
        ), (layout, specs)


def test_layout_stat_and_dispatch_under_fsdp():
    reg.reset()
    drv = _drive_layout("data2xfsdp4", n_msgs=6)
    assert drv.layout == "data×fsdp"
    assert drv.stats["layout"] == "data×fsdp"
    spans = reg.report()["spans"]
    assert spans["train.dispatch"]["count"] == drv.steps == 6


def test_build_rejects_model_axis_sharded_batch():
    """Satellite gate: an fsdp/tp-sharded BATCH compiles a wrong
    program — build refuses it by name at build time."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blendjax.parallel import resolve_layout

    mesh = resolve_layout("data4xtp2").create_mesh()
    img = np.zeros((B, HW, HW, 4), np.uint8)
    bad = jax.device_put(img, NamedSharding(mesh, P("tp")))
    with pytest.raises(ValueError, match="tp"):
        MeshTrainDriver.build(
            _model(), mesh, img, layout="data4xtp2",
            aot_batch={"image": bad},
        )


def test_reservoir_rejects_model_axis_ring():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blendjax.parallel import resolve_layout

    mesh = resolve_layout("data4xtp2").create_mesh()
    with pytest.raises(ValueError, match="tp"):
        SampleReservoir(64, sharding=NamedSharding(mesh, P("tp")))


def test_fsdp_hbm_ledger_fraction():
    """Satellite: the ledger's per-device memory figures
    (memory_analysis of the compiled sharded step) under data×fsdp are
    a ~1/|fsdp| fraction of the replicated layout's — the measured
    basis of the beyond-one-chip HBM contract."""
    from blendjax.obs.devledger import ledger
    from blendjax.parallel import resolve_layout

    def figures(layout):
        reg.reset()
        ledger.reset()
        mesh = resolve_layout(layout).create_mesh()
        bs = batch_sharding(mesh)
        # small spatial geometry so the train STATE (params + adam
        # moments), not conv activations, dominates the peak — the
        # regime the fraction contract speaks to
        img = np.zeros((B, 16, 16, 4), np.uint8)
        MeshTrainDriver.build(
            _model(), mesh, img, layout=layout, aot=True,
            aot_batch={
                "image": jax.device_put(img, bs),
                "xy": jax.device_put(
                    np.zeros((B, 8, 2), np.float32), bs
                ),
            },
            buckets=(B,), sync_every=0, inflight=2,
        )
        g = reg.report()["gauges"]
        return g["device.argument_bytes"], g["device.hbm_peak_bytes"]

    arg_rep, hbm_rep = figures("data8")
    arg_f, hbm_f = figures("data2xfsdp4")
    # argument bytes are state-dominated: ~|fsdp|=4 with slack for the
    # replicated biases and the batch slice; hbm peak adds temps
    assert arg_rep / arg_f > 2.5, (arg_rep, arg_f)
    assert hbm_rep / hbm_f > 2, (hbm_rep, hbm_f)
