"""Models, train steps, checkpointing, and image ops on the CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from blendjax.models import (  # noqa: E402
    CubeRegressor,
    Discriminator,
    PolicyValueNet,
    StreamFormer,
)
from blendjax.ops import (  # noqa: E402
    gamma_correct,
    normalize_uint8,
    random_flip,
    uint8_gamma_normalize,
)
from blendjax.parallel import batch_sharding, create_mesh  # noqa: E402
from blendjax.train import (  # noqa: E402
    CheckpointManager,
    corner_loss,
    make_eval_step,
    make_supervised_step,
    make_train_state,
)


def _batch(b=8, h=64, w=64, rng=None):
    rng = rng or np.random.default_rng(0)
    return {
        "image": rng.integers(0, 255, (b, h, w, 4), dtype=np.uint8),
        "xy": rng.uniform(0, 64, (b, 8, 2)).astype(np.float32),
    }


def test_cube_regressor_trains_loss_decreases():
    mesh = create_mesh({"data": 8})
    sharding = batch_sharding(mesh)
    model = CubeRegressor(features=(8, 16))
    batch = {
        k: jax.device_put(v, sharding) for k, v in _batch().items()
    }
    state = make_train_state(
        model, jnp.zeros((8, 64, 64, 4), jnp.uint8), learning_rate=1e-2,
        mesh=mesh,
    )
    step = make_supervised_step(mesh=mesh, batch_sharding=sharding)
    state, m0 = step(state, batch)
    losses = [float(m0["loss"])]
    for _ in range(10):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.9, losses
    assert int(state.step) == 11


def test_eval_step_metrics():
    model = CubeRegressor(features=(8,))
    state = make_train_state(model, jnp.zeros((2, 32, 32, 4), jnp.uint8))
    ev = make_eval_step()
    m = ev(state, _batch(b=2, h=32, w=32))
    assert np.isfinite(float(m["loss"])) and np.isfinite(float(m["px_err"]))


def test_corner_loss_normalization():
    pred = jnp.zeros((2, 8, 2))
    xy = jnp.full((2, 8, 2), 32.0)
    full = corner_loss(pred, xy, image_shape=(64, 64))
    np.testing.assert_allclose(float(full), 0.25, atol=1e-6)


def test_discriminator_and_policy_shapes():
    d = Discriminator(features=(8, 16))
    params = d.init(jax.random.key(0), jnp.zeros((2, 64, 64, 4), jnp.uint8))
    logits = d.apply(params, jnp.zeros((2, 64, 64, 4), jnp.uint8))
    assert logits.shape == (2,)
    p = PolicyValueNet(action_dim=1)
    pp = p.init(jax.random.key(0), jnp.zeros((3, 4)))
    mean, log_std, value = p.apply(pp, jnp.zeros((3, 4)))
    assert mean.shape == (3, 1) and log_std.shape == (1,) and value.shape == (3,)


def test_streamformer_with_ring_attention_on_mesh():
    mesh = create_mesh({"data": 2, "seq": 4})
    model = StreamFormer(
        patch=8, dim=32, depth=1, num_heads=4, use_ring=True, mesh=mesh
    )
    imgs = np.zeros((2, 32, 32, 4), np.uint8)  # 16 tokens / 4 seq shards
    sharding = NamedSharding(mesh, P("data"))
    imgs = jax.device_put(imgs, sharding)
    params = model.init(jax.random.key(0), imgs)["params"]
    out = jax.jit(lambda p, x: model.apply({"params": p}, x))(params, imgs)
    assert out.shape == (2, 16)
    # equivalence: same params, ring vs plain attention
    plain = StreamFormer(patch=8, dim=32, depth=1, num_heads=4, use_ring=False)
    out2 = plain.apply({"params": params}, np.zeros((2, 32, 32, 4), np.uint8))
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(out2), atol=2e-2
    )


def test_checkpoint_save_restore(tmp_path):
    model = CubeRegressor(features=(8,))
    state = make_train_state(model, jnp.zeros((2, 32, 32, 4), jnp.uint8))
    step = make_supervised_step()
    state, _ = step(state, _batch(b=2, h=32, w=32))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(int(state.step), state)
    assert mgr.latest_step() == 1
    fresh = make_train_state(model, jnp.zeros((2, 32, 32, 4), jnp.uint8))
    restored = mgr.restore(fresh)
    assert int(restored.step) == 1
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(restored.params)[0]),
        np.asarray(jax.tree.leaves(state.params)[0]),
    )
    mgr.close()


def test_image_ops():
    x = np.random.default_rng(0).integers(0, 255, (2, 8, 8, 4), np.uint8)
    n = normalize_uint8(jnp.asarray(x), jnp.float32)
    assert float(n.max()) <= 1.0
    g = gamma_correct(n, 2.2)
    assert g.shape == n.shape and float(g.min()) >= 0.0
    # pallas kernel (interpret mode on CPU) matches the jnp path
    ref = np.asarray(gamma_correct(normalize_uint8(jnp.asarray(x), jnp.float32)))
    from blendjax.ops.image import _pallas_gamma_normalize

    pk = np.asarray(
        _pallas_gamma_normalize(jnp.asarray(x), gamma=2.2, interpret=True)
    )
    np.testing.assert_allclose(pk, ref, atol=1e-5)
    # flip augmentation flips exactly the samples the key's bernoulli bits
    # select (deterministic given the key)
    key = jax.random.key(0)
    xb = np.random.default_rng(1).integers(0, 255, (16, 4, 6, 3), np.uint8)
    f = np.asarray(random_flip(key, jnp.asarray(xb)))
    bits = np.asarray(jax.random.bernoulli(key, 0.5, (16,)))
    assert bits.any() and not bits.all()  # both behaviors exercised
    for i in range(16):
        expect = xb[i][:, ::-1] if bits[i] else xb[i]
        np.testing.assert_array_equal(f[i], expect)


def test_models_accept_prenormalized_floats():
    """uint8 and uint8/255-float inputs must agree (shared normalize
    guard; CubeRegressor once double-divided floats by 255)."""
    for model in (
        CubeRegressor(features=(8,)),
        Discriminator(features=(8,)),
        StreamFormer(patch=8, dim=32, depth=1, num_heads=4),
    ):
        x8 = np.random.default_rng(2).integers(0, 255, (2, 32, 32, 4), np.uint8)
        xf = (x8 / 255.0).astype(np.float32)
        params = model.init(jax.random.key(0), x8)
        np.testing.assert_allclose(
            np.asarray(model.apply(params, x8)),
            np.asarray(model.apply(params, xf)),
            atol=1e-2,
        )


def test_ring_attention_degrades_without_seq_axis():
    from blendjax.parallel import ring_attention
    from blendjax.parallel.ring import reference_attention

    mesh = create_mesh({"data": 8})
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 8, 2, 4)).astype(np.float32))
        for _ in range(3)
    )
    out = ring_attention(q, k, v, mesh)  # no 'seq' axis -> plain attention
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(reference_attention(q, k, v)), atol=1e-6
    )


def test_pallas_gamma_odd_row_count():
    """Row counts with no divisor near 256 must still tile (VMEM bound)."""
    from blendjax.ops.image import _pallas_gamma_normalize

    x = np.random.default_rng(4).integers(0, 255, (1, 37, 8, 4), np.uint8)
    out = np.asarray(
        _pallas_gamma_normalize(jnp.asarray(x), gamma=2.2, interpret=True)
    )
    ref = np.asarray(gamma_correct(normalize_uint8(jnp.asarray(x), jnp.float32)))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_streamformer_remat_matches_baseline_grads():
    """remat=True (nn.remat blocks — recompute activations on backward)
    produces identical loss and gradients to the baseline."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from blendjax.models import StreamFormer

    imgs = np.random.default_rng(0).integers(
        0, 255, (2, 32, 32, 4), np.uint8
    )
    kw = dict(patch=8, dim=32, depth=2, num_heads=4, num_outputs=4,
              dtype=jnp.float32)
    base = StreamFormer(**kw)
    rmt = StreamFormer(remat=True, **kw)
    params = base.init(jax.random.key(0), imgs)["params"]

    def loss(model, p):
        return jnp.mean(model.apply({"params": p}, imgs) ** 2)

    l0, g0 = jax.value_and_grad(lambda p: loss(base, p))(params)
    l1, g1 = jax.value_and_grad(lambda p: loss(rmt, p))(params)
    assert np.allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        g0, g1,
    )


def test_gradient_accumulation_matches_full_batch():
    """accum_steps=4 produces (numerically) the same update as one full
    batch: mean-of-micro-losses and mean-of-micro-grads equal the
    full-batch values for a mean-reduced loss."""
    import jax
    import numpy as np

    from blendjax.models import CubeRegressor
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import make_supervised_step, make_train_state

    mesh = create_mesh({"data": -1})
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(0)
    batch = {
        "image": rng.integers(0, 255, (8, 32, 32, 4), np.uint8),
        "xy": rng.random((8, 8, 2), np.float32) * 32,
    }
    import optax

    # SGD: the update is linear in the gradients, so accumulated-vs-full
    # comparison isn't confounded by Adam's sign sensitivity at ~0 grads.
    s0 = make_train_state(
        CubeRegressor(), batch["image"], mesh=mesh,
        optimizer=optax.sgd(0.01),
    )
    step1 = make_supervised_step(mesh=mesh, batch_sharding=sh, donate=False)
    step4 = make_supervised_step(
        mesh=mesh, batch_sharding=sh, donate=False, accum_steps=4
    )
    s1, m1 = step1(s0, batch)
    s4, m4 = step4(s0, batch)
    assert np.allclose(float(m1["loss"]), float(m4["loss"]), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-6
        ),
        s1.params, s4.params,
    )
    with pytest.raises(ValueError, match="not divisible"):
        step3 = make_supervised_step(
            mesh=mesh, batch_sharding=sh, donate=False, accum_steps=3
        )
        step3(s0, batch)


def test_augmentation_ops_semantics():
    """On-device augmentation suite: static shapes/dtypes, per-sample
    randomness, and exact semantic checks per op."""
    from blendjax.ops.augment import (
        color_jitter,
        make_augment,
        random_crop,
        random_cutout,
        random_flip,
    )

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 255, (8, 16, 24, 4), np.uint8)
    key = jax.random.key(7)

    flipped = np.asarray(jax.jit(random_flip)(key, imgs))
    assert flipped.shape == imgs.shape and flipped.dtype == np.uint8
    # every sample is either the original or its exact mirror
    per_sample = [
        (flipped[i] == imgs[i]).all()
        or (flipped[i] == imgs[i, :, ::-1]).all()
        for i in range(8)
    ]
    assert all(per_sample)
    assert any((flipped[i] != imgs[i]).any() for i in range(8))

    cropped = np.asarray(jax.jit(random_crop)(key, imgs))
    assert cropped.shape == imgs.shape and cropped.dtype == np.uint8

    jit_jitter = jax.jit(color_jitter)
    jittered = np.asarray(jit_jitter(key, imgs))
    assert jittered.shape == imgs.shape and jittered.dtype == np.uint8
    # identity-strength jitter is a no-op (round-trip through [0,1])
    ident = np.asarray(
        jax.jit(
            lambda k, x: color_jitter(k, x, brightness=0.0, contrast=0.0)
        )(key, imgs)
    )
    np.testing.assert_array_equal(ident, imgs)

    cut = np.asarray(jax.jit(random_cutout)(key, imgs))
    assert cut.shape == imgs.shape
    # each sample has a zeroed region (fill=0 over a square)
    assert all((cut[i] == 0).any() for i in range(8))

    aug = make_augment(random_flip, random_crop)
    out1 = np.asarray(jax.jit(aug)(key, imgs))
    out2 = np.asarray(jax.jit(aug)(key, imgs))
    np.testing.assert_array_equal(out1, out2)  # same key -> deterministic
    out3 = np.asarray(jax.jit(aug)(jax.random.key(8), imgs))
    assert (out3 != out1).any()


def test_supervised_step_with_on_device_augmentation():
    """augment= runs inside the jitted step, sharded with the batch, and
    the per-step key folds the step counter (deterministic across
    reruns; different across steps)."""
    import optax

    from blendjax.models import CubeRegressor
    from blendjax.ops.augment import make_augment, random_flip
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import make_supervised_step, make_train_state

    mesh = create_mesh({"data": -1})
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(1)
    batch = {
        "image": jax.device_put(
            rng.integers(0, 255, (8, 32, 32, 4), np.uint8), sh
        ),
        "xy": jax.device_put(
            (rng.random((8, 8, 2)) * 32).astype(np.float32), sh
        ),
    }

    def make(seed):
        s0 = make_train_state(
            CubeRegressor(features=(8,)), np.asarray(batch["image"]),
            mesh=mesh, optimizer=optax.sgd(0.01),
        )
        step = make_supervised_step(
            mesh=mesh, batch_sharding=sh, donate=False,
            augment=make_augment(random_flip),
            augment_rng=jax.random.key(seed),
        )
        return s0, step

    s0, step = make(0)
    sA, mA = step(s0, batch)
    sA2, mA2 = step(s0, batch)
    assert float(mA["loss"]) == float(mA2["loss"])  # deterministic
    sB, mB = step(sA, batch)  # next step folds a different key
    assert np.isfinite(float(mB["loss"]))
    # a different augment seed gives a different trajectory
    s0c, stepc = make(123)
    _, mC = stepc(s0c, batch)
    assert np.isfinite(float(mC["loss"]))


def test_chunked_step_with_augment_matches_sequential():
    """make_chunked_supervised_step(augment=...) folds the in-scan step
    counter, so one scanned superbatch trains identically to K
    sequential per-batch augmented steps (same keys, same trajectory)."""
    import optax

    from blendjax.models import CubeRegressor
    from blendjax.ops.augment import make_augment, random_flip
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import (
        make_chunked_supervised_step,
        make_supervised_step,
        make_train_state,
    )

    mesh = create_mesh({"data": -1})
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(7)
    K, B = 3, 4
    images = rng.integers(0, 255, (K, B, 32, 32, 4), np.uint8)
    xys = (rng.random((K, B, 8, 2)) * 32).astype(np.float32)
    aug = make_augment(random_flip)
    key = jax.random.key(42)
    s0 = make_train_state(
        CubeRegressor(features=(8,)), images[0], mesh=mesh,
        optimizer=optax.sgd(0.01),
    )

    seq = make_supervised_step(
        mesh=mesh, batch_sharding=sh, donate=False,
        augment=aug, augment_rng=key,
    )
    s_seq, seq_losses = s0, []
    for k in range(K):
        s_seq, m = seq(s_seq, {"image": images[k], "xy": xys[k]})
        seq_losses.append(float(m["loss"]))

    chunked = make_chunked_supervised_step(
        donate=False, augment=aug, augment_rng=key
    )
    s_chk, mc = chunked(s0, {"image": images, "xy": xys})

    np.testing.assert_allclose(np.asarray(mc["loss"]), seq_losses, rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        s_seq.params, s_chk.params,
    )
    # sanity: the augment actually changed the trajectory vs no-augment
    plain = make_chunked_supervised_step(donate=False)
    _, mp = plain(s0, {"image": images, "xy": xys})
    assert not np.allclose(np.asarray(mp["loss"]), np.asarray(mc["loss"]))


def test_paired_geometric_augmentation_keeps_labels_synced():
    """random_flip_with_points / random_crop_with_points transform image
    and pixel-space labels together: a marker pixel's new location
    equals the transformed point, exactly."""
    from blendjax.ops.augment import (
        random_crop_with_points,
        random_flip_with_points,
    )

    b, h, w = 8, 16, 24
    imgs = np.zeros((b, h, w, 3), np.uint8)
    pts = np.empty((b, 1, 2), np.float32)  # (x, y)
    rng = np.random.default_rng(3)
    for i in range(b):
        y, x = int(rng.integers(0, h)), int(rng.integers(0, w))
        imgs[i, y, x] = 255
        pts[i, 0] = (x, y)

    key = jax.random.key(11)
    fi, fp = jax.jit(random_flip_with_points)(key, imgs, pts)
    fi, fp = np.asarray(fi), np.asarray(fp)
    flipped_any = False
    for i in range(b):
        ys, xs, _ = np.nonzero(fi[i])
        assert (xs[0], ys[0]) == (int(fp[i, 0, 0]), int(fp[i, 0, 1]))
        flipped_any |= (fi[i] != imgs[i]).any()
    assert flipped_any

    ci, cp = jax.jit(random_crop_with_points)(key, imgs, pts)
    ci, cp = np.asarray(ci), np.asarray(cp)
    assert ci.shape == imgs.shape
    moved_any = False
    for i in range(b):
        x2, y2 = cp[i, 0]
        if 0 <= x2 < w and 0 <= y2 < h:
            # marker may be duplicated by edge padding; the labeled
            # location must hold the marker value
            assert (ci[i, int(y2), int(x2)] == 255).all()
        moved_any |= (cp[i] != pts[i]).any()
    assert moved_any
