"""True multi-process JAX tier (VERDICT r1 item 5).

Two coordinated OS processes (``jax.distributed`` over a localhost
coordinator, 4 virtual CPU devices each = an 8-device global mesh) run
``tests/mp_worker.py``: DeviceFeeder(multihost=True) global batch
assembly, a cross-process collective, and the multihost tile-decode
path — the CPU mirror of a 2-host TPU pod, in the spirit of the
reference's ``mp.Process`` two-machine tests
(``tests/test_launcher.py:47-91``).
"""

import functools
import os
import socket
import subprocess
import sys
import textwrap

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# The minimal cross-process program: jax.distributed rendezvous + one
# process_allgather — the first collective the real workers run. On jax
# builds whose CPU backend can't execute cross-process computations
# ("Multiprocess computations aren't implemented on the CPU backend",
# the current 0.4.x state) it fails fast with that error.
_PROBE = textwrap.dedent(
    """
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address="127.0.0.1:%d",
        num_processes=2,
        process_id=int(sys.argv[1]),
    )
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    multihost_utils.process_allgather(jnp.ones((1,)))
    print("mp-probe-ok")
    """
)


@functools.lru_cache(maxsize=1)
def _cpu_multiprocess_capability() -> tuple:
    """``(supported, detail)`` — a real capability probe (two
    coordinated processes running one cross-process collective), not a
    blanket marker: when a jax upgrade teaches the CPU backend
    multi-process execution (ROADMAP item 3), these tests un-skip by
    themselves."""
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _PROBE % port, str(i)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs, ok = [], True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            out = (out or "") + "\n[probe timeout]"
        outs.append(out or "")
        ok = ok and p.returncode == 0 and "mp-probe-ok" in (out or "")
    if ok:
        return True, "cross-process allgather ran"
    # the last non-empty line names the failure (the backend refusal on
    # today's jax)
    lines = [
        line.strip()
        for out in outs
        for line in out.splitlines()
        if line.strip()
    ]
    return False, (lines[-1] if lines else "no probe output")[:200]


def _require_multiprocess_backend() -> None:
    supported, detail = _cpu_multiprocess_capability()
    if not supported:
        pytest.skip(
            "jax CPU backend cannot run cross-process computations on "
            f"this build (probe: {detail!r}) — the real multi-process "
            "topology is ROADMAP item 3"
        )


def _run_workers(mode=None, nproc=2):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the parent's pytest conftest forced 8 local devices; children set
    # their own count BEFORE importing jax, so scrub inherited state
    env.pop("JAX_NUM_PROCESSES", None)
    extra = [mode] if mode else []
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nproc), str(port)] + extra,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_global_batch_assembly_and_tile_decode():
    """Global assembly + collective + tile decode (chunk=1 and the
    chunk=4 lockstep superbatch, both bit-exact per shard)."""
    _require_multiprocess_backend()
    procs, outs = _run_workers()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"mp_worker {i}/2 ok" in out


def test_two_process_divergent_ref_fails_loudly():
    """Processes shipping different reference content must ERROR on the
    fleet-digest all-gather, not silently corrupt decoded rows (ADVICE
    r2 medium)."""
    _require_multiprocess_backend()
    procs, outs = _run_workers(mode="divergent-ref")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"mp_worker {i}/2 divergence-detected" in out
