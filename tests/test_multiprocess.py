"""True multi-process JAX tier (VERDICT r1 item 5).

Two coordinated OS processes (``jax.distributed`` over a localhost
coordinator, 4 virtual CPU devices each = an 8-device global mesh) run
``tests/mp_worker.py``: DeviceFeeder(multihost=True) global batch
assembly, a cross-process collective, and the multihost tile-decode
path — the CPU mirror of a 2-host TPU pod, in the spirit of the
reference's ``mp.Process`` two-machine tests
(``tests/test_launcher.py:47-91``).
"""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(os.path.dirname(__file__), "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(mode=None, nproc=2):
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # the parent's pytest conftest forced 8 local devices; children set
    # their own count BEFORE importing jax, so scrub inherited state
    env.pop("JAX_NUM_PROCESSES", None)
    extra = [mode] if mode else []
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nproc), str(port)] + extra,
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_global_batch_assembly_and_tile_decode():
    """Global assembly + collective + tile decode (chunk=1 and the
    chunk=4 lockstep superbatch, both bit-exact per shard)."""
    procs, outs = _run_workers()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"mp_worker {i}/2 ok" in out


def test_two_process_divergent_ref_fails_loudly():
    """Processes shipping different reference content must ERROR on the
    fleet-digest all-gather, not silently corrupt decoded rows (ADVICE
    r2 medium)."""
    procs, outs = _run_workers(mode="divergent-ref")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"mp_worker {i}/2 divergence-detected" in out
