"""blendjax.obs: histogram exactness, frame lineage, the stall doctor,
the exporters (Prometheus / JSONL / Chrome trace), distributed frame
tracing, and the SLO watchdog + flight recorder."""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from blendjax.obs import (
    VERDICTS,
    FlightRecorder,
    JsonlExporter,
    Slo,
    SloWatchdog,
    StatsReporter,
    chrome_trace,
    diagnose,
    prometheus_text,
    start_http_exporter,
    write_chrome_trace,
)
from blendjax.obs.lineage import (
    PUB_MONO_KEY,
    PUB_WALL_KEY,
    SEQ_KEY,
    FrameLineage,
    strip_stamps,
)
from blendjax.obs.trace import (
    TRACE_KEY,
    TRACES_KEY,
    FrameTraceCollector,
    iter_traces,
    make_trace,
    pop_traces,
    stage as trace_stage,
    stamp_batch,
)
from blendjax.utils.metrics import Histogram, Metrics

WILD = "tcp://127.0.0.1:*"


# -- histograms --------------------------------------------------------------


def test_histogram_quantiles_within_bucket_resolution():
    h = Histogram()
    vals = np.linspace(0.001, 10.0, 5000)
    for v in vals:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 5000
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(10.0)
    # log-bucket midpoint estimate: within ~4.5% relative error
    for q, true in ((0.5, np.quantile(vals, 0.5)),
                    (0.95, np.quantile(vals, 0.95)),
                    (0.99, np.quantile(vals, 0.99))):
        assert h.quantile(q) == pytest.approx(true, rel=0.05)


def test_histogram_nonpositive_values_sort_below_everything():
    h = Histogram()
    for v in (-0.5, 0.0, 1.0, 2.0, 4.0):
        h.observe(v)
    assert h.count == 5
    assert h.zeros == 2
    assert h.quantile(0.0) == -0.5  # exact min preserved
    assert h.quantile(1.0) == 4.0
    assert h.quantile(0.5) == pytest.approx(1.0, rel=0.05)


def test_histogram_exact_counts_under_concurrent_observe():
    """Lock-exactness: N threads x M observes never lose a count, and
    the bucket counts sum exactly to the observe calls."""
    m = Metrics()
    threads_n, per_thread = 8, 2000

    def work(seed):
        rng = np.random.default_rng(seed)
        for _ in range(per_thread):
            m.observe("conc", float(rng.random()) + 1e-6)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(threads_n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s = m.histograms()["conc"]
    assert s["count"] == threads_n * per_thread
    buckets = m.histogram_buckets()["conc"]
    cum, count, _ = buckets
    assert cum[-1][1] == count == threads_n * per_thread


def test_span_and_histogram_counts_stay_in_lockstep_concurrently():
    """Spans feed same-name histograms under ONE lock acquisition:
    histogram count == span count at any concurrency (the bench
    acceptance check, hermetic version)."""
    m = Metrics()

    def work():
        for _ in range(500):
            with m.span("s"):
                pass

    ts = [threading.Thread(target=work) for _ in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert m.spans()["s"]["count"] == 3000
    assert m.histograms()["s"]["count"] == 3000
    assert "p99_ms" in m.spans()["s"]


def test_report_is_a_consistent_snapshot_under_gauge_churn():
    """gauge()/report() both take the registry lock (the PR-4 fix for
    'dictionary changed size during iteration')."""
    m = Metrics()
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            m.gauge(f"g{i % 997}", i)
            i += 1

    ts = [threading.Thread(target=churn) for _ in range(4)]
    for t in ts:
        t.start()
    try:
        for _ in range(200):
            m.report()  # raced the writers before the lock
    finally:
        stop.set()
        for t in ts:
            t.join()


# -- frame lineage -----------------------------------------------------------


def _stamped(btid, seq, age_s=0.0, **extra):
    return {
        "btid": btid,
        SEQ_KEY: seq,
        PUB_WALL_KEY: time.time() - age_s,
        PUB_MONO_KEY: time.monotonic() - age_s,
        **extra,
    }


def test_lineage_pops_stamps_and_tracks_staleness():
    ln = FrameLineage()
    msg = _stamped(3, 0, age_s=0.5, image=np.zeros(2))
    ln.ingest(msg)
    assert SEQ_KEY not in msg and PUB_WALL_KEY not in msg
    assert "image" in msg  # payload untouched
    rep = ln.report()["3"]
    assert rep["received"] == 1
    assert rep["seq_gaps"] == 0
    assert rep["e2e_staleness_ms"]["p95"] == pytest.approx(500, rel=0.1)
    assert ln.staleness_p95_s() == pytest.approx(0.5, rel=0.1)


def test_lineage_gap_and_reorder_accounting_is_exact():
    ln = FrameLineage()
    for seq in (0, 1, 4, 3, 5):  # drop 2+one-of(3,4)=gap 2, then reorder
        ln.ingest(_stamped(7, seq))
    rep = ln.report()["7"]
    assert rep["seq_gaps"] == 2
    assert rep["seq_reorders"] == 1
    assert rep["last_seq"] == 5
    assert ln.total_gaps() == 2


def test_lineage_producer_respawn_resets_tracking_not_reorder_storm():
    """A respawned producer (launcher reuses the btid, fresh publisher
    numbers from 0) must read as a RESTART: zero reorders, and drop
    detection works immediately in the new incarnation."""
    ln = FrameLineage()
    for seq in range(100):
        ln.ingest(_stamped(5, seq))
    # respawn: seq restarts at 0, then a real drop (skip seq 2)
    for seq in (0, 1, 3, 4):
        ln.ingest(_stamped(5, seq))
    rep = ln.report()["5"]
    assert rep["restarts"] == 1
    assert rep["seq_reorders"] == 0  # no post-respawn reorder storm
    assert rep["seq_gaps"] == 1      # the real drop, flagged at once
    assert rep["last_seq"] == 4


def test_lineage_interleaved_producers_are_not_gaps():
    """Round-robin interleave of independent producers (what the
    sharded pool's fan-in looks like) must count ZERO gaps: tracking is
    per producer."""
    ln = FrameLineage()
    for seq in range(20):
        for btid in (0, 1, 2):
            ln.ingest(_stamped(btid, seq))
    assert ln.total_gaps() == 0
    for btid in ("0", "1", "2"):
        assert ln.report()[btid]["seq_gaps"] == 0


def test_lineage_unstamped_messages_pass_through():
    ln = FrameLineage()
    msg = {"btid": 0, "image": np.zeros(2)}
    ln.ingest(msg)
    assert ln.report() == {}


def test_lineage_telemetry_fleet_view():
    ln = FrameLineage()
    ln.ingest(_stamped(0, 0, _telemetry={"seq": 0, "mps": 12.5,
                                         "spans": {}, "counters": {}}))
    rep = ln.report()["0"]
    assert rep["telemetry"]["mps"] == 12.5
    assert rep["telemetry_age_s"] >= 0.0


def test_strip_stamps_for_replay():
    msg = _stamped(0, 3, _telemetry={})
    out = strip_stamps(msg)
    assert out is msg
    assert set(msg) == {"btid"}


# -- stamps over a real socket ----------------------------------------------


def test_publisher_stamps_and_stream_accounts_them():
    """DataPublisherSocket stamps -> RemoteStream pops + accounts; the
    consumer-visible items carry NO stamp keys, and the process-wide
    lineage sees exact per-producer sequence accounting."""
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pub = DataPublisherSocket(WILD, btid=11, telemetry_every=2)
    stream = RemoteStream([pub.addr], timeoutms=5000, max_items=5)
    t = threading.Thread(
        target=lambda: [
            pub.publish(image=np.zeros((4, 4), np.uint8), frameid=i)
            for i in range(5)
        ],
        daemon=True,
    )
    t.start()
    items = list(stream)
    t.join(timeout=5)
    pub.close()
    assert len(items) == 5
    for it in items:
        assert SEQ_KEY not in it and PUB_WALL_KEY not in it
        assert "_telemetry" not in it
    rep = lineage.report()["11"]
    assert rep["received"] == 5
    assert rep["last_seq"] == 4
    assert rep["seq_gaps"] == 0
    # telemetry_every=2: snapshots piggybacked on seq 0/2/4 — latest won
    assert rep["telemetry"]["seq"] in (2, 4)
    assert metrics.counters.get("wire.seq_gaps", 0) == 0
    assert metrics.histograms()["wire.e2e_staleness_s"]["count"] == 5


def test_sharded_ingest_partitions_do_not_fake_gaps_but_real_gaps_flag():
    """Two producers partitioned across two shard workers: the
    round-robin interleave counts zero gaps; a producer that SKIPS a
    seq (simulated drop) is flagged with the exact gap size."""
    from blendjax.data.shard_ingest import ShardedHostIngest
    from blendjax.data.stream import RemoteStream, partition_addresses
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pubs = [
        DataPublisherSocket(WILD, btid=i, telemetry_every=0)
        for i in range(2)
    ]
    n = 8

    def feed(pub, skip=None):
        for i in range(n):
            if i == skip:
                pub._seq += 1  # simulate a dropped message: seq skips
                continue
            pub.publish(image=np.full((2, 2), pub.btid, np.uint8),
                        frameid=i)

    shards = partition_addresses([p.addr for p in pubs], 2)
    assert len(shards) == 2
    streams = [
        # track_gaps=True: shards see DISJOINT producer subsets, so gap
        # accounting is sound despite the worker slot (what the
        # pipeline's shard_stream passes).
        RemoteStream(s, timeoutms=5000, worker_index=i, num_workers=2,
                     track_gaps=True)
        for i, s in enumerate(shards)
    ]
    ingest = ShardedHostIngest(
        streams, batch_size=2, max_messages=2 * n - 1
    )
    threads = [
        threading.Thread(target=feed, args=(pubs[0],), daemon=True),
        threading.Thread(target=feed, args=(pubs[1], 3), daemon=True),
    ]
    for t in threads:
        t.start()
    batches = list(ingest)
    for t in threads:
        t.join(timeout=5)
    for p in pubs:
        p.close()
    assert sum(len(b["_meta"]) for b in batches) >= 2 * n - 4
    rep = lineage.report()
    assert rep["0"]["seq_gaps"] == 0  # clean producer: no false gaps
    assert rep["1"]["seq_gaps"] == 1  # the simulated drop, exactly
    assert metrics.counters.get("wire.seq_gaps", 0) == 1


def test_shared_fanin_consumers_do_not_fake_gaps():
    """Two consumers splitting ONE producer fan-in (DataLoader-worker
    shape: same addresses, num_workers=2) each see a strided
    subsequence — the auto track_gaps default must count ZERO gaps,
    while staleness accounting stays on."""
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pub = DataPublisherSocket(WILD, btid=4, telemetry_every=0)
    streams = [
        RemoteStream([pub.addr], timeoutms=5000, worker_index=i,
                     num_workers=2, max_items=16)
        for i in range(2)
    ]
    assert all(not s.track_gaps for s in streams)

    def drain(s, out):
        out.extend(s)

    outs: list = [[], []]
    ts = [
        threading.Thread(target=drain, args=(s, o), daemon=True)
        for s, o in zip(streams, outs)
    ]
    for t in ts:
        t.start()
    time.sleep(0.3)  # both PULL peers connected before publishing
    for i in range(16):
        pub.publish(image=np.zeros((2, 2), np.uint8), frameid=i)
    for t in ts:
        t.join(timeout=10)
    pub.close()
    assert sum(len(o) for o in outs) == 16
    rep = lineage.report()["4"]
    assert rep["seq_gaps"] == 0 and rep["seq_reorders"] == 0
    assert rep["e2e_staleness_ms"]["count"] == 16  # staleness stays on
    assert metrics.counters.get("wire.seq_gaps", 0) == 0


def test_nonfinite_staleness_stamp_does_not_kill_ingest():
    """A corrupted producer clock (NaN/inf _pub_wall) must not raise
    out of lineage.ingest and kill the receive loop."""
    ln = FrameLineage()
    for wall in (float("nan"), float("inf"), float("-inf")):
        ln.ingest({"btid": 9, SEQ_KEY: 0, PUB_WALL_KEY: wall,
                   PUB_MONO_KEY: 0.0})  # staleness = now - wall = ±inf/nan
    h = Histogram()
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(1.0)
    s = h.summary()
    assert s["count"] == 1  # finite sample only
    assert s["nonfinite"] == 2
    assert h.quantile(0.5) == 1.0


# -- stall doctor ------------------------------------------------------------


def _report(spans=None, counters=None, gauges=None):
    return {
        "spans": {
            k: {"count": 10, "total_s": v} for k, v in (spans or {}).items()
        },
        "counters": counters or {},
        "gauges": gauges or {},
        "histograms": {},
    }


def test_doctor_step_bound_on_backpressure():
    v = diagnose(_report(
        spans={"ingest.recv": 1.0, "ingest.queue_wait": 0.1,
               "train.dispatch": 8.0},
        counters={"ingest.queue_full_waits": 40},
    ))
    assert v.kind == "step-bound"
    assert "queue_full_waits=40" in v.reason


def test_doctor_step_bound_on_driver_ring():
    v = diagnose(
        _report(spans={"train.dispatch": 1.0, "driver.ring_wait": 4.0}),
        driver={"host_blocks": 25},
    )
    assert v.kind == "step-bound"


def test_doctor_feed_bound():
    v = diagnose(_report(
        spans={"feed.throttle_wait": 5.0, "feed.place": 1.0,
               "train.dispatch": 2.0},
        counters={"feed.throttle_blocks": 17},
    ))
    assert v.kind == "feed-bound"
    assert "throttle_blocks=17" in v.reason


def test_doctor_decode_bound():
    v = diagnose(_report(
        spans={"decode.dispatch": 6.0, "train.dispatch": 2.0,
               "ingest.queue_wait": 1.0},
    ))
    assert v.kind == "decode-bound"


def test_doctor_step_bound_on_pinned_queue_depth_gauge():
    """queue_depth_hwm pinned at the prefetch bound is backpressure
    evidence even before a queue_full_wait is ever counted."""
    v = diagnose(
        _report(
            spans={"train.dispatch": 5.0, "ingest.queue_wait": 0.1},
            gauges={"ingest.queue_depth_hwm": 2},
        ),
        prefetch=2,
    )
    assert v.kind == "step-bound"
    assert "queue_depth_hwm=2" in v.reason


def test_doctor_sharded_recv_time_does_not_fake_starvation():
    """N shard workers parked in recv bank ~N x wall of ingest.recv*
    span time concurrently; that must not classify a healthy run as
    starving when the consumer itself never waits on the queue."""
    v = diagnose(_report(spans={
        "ingest.recv.shard0": 2.0, "ingest.recv.shard1": 2.0,
        "ingest.recv.shard2": 2.0, "ingest.recv.shard3": 2.0,
        "ingest.queue_wait": 0.1, "train.dispatch": 2.0,
        "feed.place": 1.0,
    }))
    assert v.kind == "balanced"


def test_doctor_wire_vs_producer_bound_split_on_staleness():
    starving = _report(
        spans={"ingest.queue_wait": 6.0, "ingest.recv": 2.0,
               "train.dispatch": 1.0},
    )
    stale_lineage = {
        "0": {"e2e_staleness_ms": {"count": 50, "p95": 900.0}},
    }
    fresh_lineage = {
        "0": {"e2e_staleness_ms": {"count": 50, "p95": 8.0}},
    }
    assert diagnose(starving, lineage=stale_lineage).kind == "wire-bound"
    assert diagnose(starving, lineage=fresh_lineage).kind == "producer-bound"
    # no lineage at all: still a verdict (producer-bound, "unstamped")
    v = diagnose(starving)
    assert v.kind == "producer-bound"
    assert "unstamped" in v.reason


def test_doctor_balanced_and_idle_and_render_shape():
    assert diagnose(_report()).kind == "idle"
    v = diagnose(_report(spans={
        "ingest.recv": 1.0, "ingest.queue_wait": 1.0, "feed.place": 1.0,
        "decode.dispatch": 1.0, "train.dispatch": 1.0,
    }))
    assert v.kind == "balanced"
    line = v.render()
    assert line.startswith("doctor: balanced — ") and "\n" not in line
    assert all(k in VERDICTS for k in (
        "step-bound", "feed-bound", "decode-bound", "wire-bound",
        "producer-bound", v.kind, "idle",
    ))


# -- exporters ---------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"[-+]?[0-9]+(\.[0-9]+)?([eE][-+]?[0-9]+)?$"
)


def _filled_registry():
    m = Metrics()
    m.count("wire.raw_bytes", 1024)
    m.count("ingest.items", 7)
    m.gauge("ingest.queue_depth", 2)
    for v in (0.001, 0.002, 0.004, 0.02):
        m.observe("ingest.recv", v)
    with m.span("feed.place"):
        pass
    return m


def test_prometheus_text_is_well_formed():
    m = _filled_registry()
    lineage_report = {
        str(b): {"received": 7, "seq_gaps": 0, "seq_reorders": 0,
                 "restarts": 0,
                 "e2e_staleness_ms": {"count": 7, "p50": 3.0, "p95": 9.0,
                                      "p99": 12.0}}
        for b in (0, 1)
    }
    text = prometheus_text(report=m.report(), lineage_report=lineage_report,
                           registry=m)
    assert text.endswith("\n")
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram|summary)$", line), line
        else:
            assert _PROM_SAMPLE.match(line), line
    # exposition grouping: all samples of one metric name are ONE
    # contiguous block (multi-producer pages are rejected by strict
    # parsers otherwise)
    names, last = [], None
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if name != last:
            names.append(name)
            last = name
    assert len(names) == len(set(names)), names
    # histogram invariants: cumulative buckets monotone, +Inf == count
    assert 'blendjax_ingest_recv_bucket{le="+Inf"} 4' in text
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("blendjax_ingest_recv_bucket")
    ]
    assert cums == sorted(cums)
    assert "blendjax_wire_raw_bytes_total 1024" in text
    assert 'blendjax_producer_e2e_staleness_ms{btid="0",quantile="0.95"} 9.0' in text
    assert 'blendjax_producer_seq_gaps_total{btid="1"} 0' in text


def test_http_exporter_serves_live_registry():
    m = _filled_registry()
    srv = start_http_exporter(port=0, registry=m)
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "blendjax_ingest_items_total 7" in body
        # a second scrape sees fresh state
        m.count("ingest.items", 1)
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert "blendjax_ingest_items_total 8" in resp.read().decode()
    finally:
        srv.close()


def test_jsonl_exporter_appends_parseable_lines(tmp_path):
    path = str(tmp_path / "snapshots.jsonl")
    ex = JsonlExporter(path)
    m = _filled_registry()
    ex.write(m.report())
    ex.write(m.report(), extra={"doctor": {"kind": "balanced"}})
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    for line in lines:
        rec = json.loads(line)
        assert rec["t"] > 0
        assert rec["report"]["counters"]["ingest.items"] == 7
    assert json.loads(lines[1])["doctor"]["kind"] == "balanced"


def test_chrome_trace_export_well_formed(tmp_path):
    m = Metrics()
    m.enable_span_events()
    with m.span("ingest.recv"):
        time.sleep(0.001)
    with m.span("feed.place"):
        pass
    obj = chrome_trace(registry=m)
    assert set(obj) == {"traceEvents", "displayTimeUnit"}
    assert len(obj["traceEvents"]) == 2
    for ev in obj["traceEvents"]:
        assert ev["ph"] == "X"
        assert set(ev) >= {"name", "cat", "ts", "dur", "pid", "tid"}
        assert ev["dur"] >= 0
    path = str(tmp_path / "trace.json")
    assert write_chrome_trace(path, registry=m) == 2
    loaded = json.load(open(path))
    assert loaded["traceEvents"][0]["name"] in ("ingest.recv", "feed.place")
    # events ring respects capacity and disable
    m.disable_span_events()
    with m.span("x"):
        pass
    assert len(m.span_events()) == 0


# -- stats reporter ----------------------------------------------------------


def test_stats_reporter_tick_logs_verdict_and_archives(tmp_path):
    m = _filled_registry()
    ln = FrameLineage()
    path = str(tmp_path / "stats.jsonl")
    rep = StatsReporter(
        interval_s=3600, registry=m, lineage=ln, jsonl_path=path,
        driver_stats=lambda: {"host_blocks": 0},
    )
    v = rep.tick()
    assert v.kind in VERDICTS
    assert rep.last_verdict is v
    rec = json.loads(open(path).read().strip())
    assert rec["doctor"]["kind"] == v.kind
    assert "lineage" in rec


def test_stats_reporter_thread_lifecycle(tmp_path):
    m = _filled_registry()
    rep = StatsReporter(interval_s=0.05, registry=m,
                        lineage=FrameLineage())
    rep.start()
    time.sleep(0.2)
    rep.stop()
    assert rep.last_verdict is not None


# -- distributed frame tracing (blendjax.obs.trace) --------------------------


def test_publisher_trace_sampling_recv_stamp_and_replay_strip():
    """trace_every=2: every 2nd message carries a `_trace` context the
    stream stamps `recv` onto; the rest carry nothing, trace_every=0
    disables stamping entirely, and strip_stamps removes the context
    on replay (recorded wall stamps would read as hours of latency)."""
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    for every, expect in ((2, 3), (0, 0)):
        pub = DataPublisherSocket(
            WILD, btid=5, telemetry_every=0, trace_every=every
        )
        stream = RemoteStream([pub.addr], timeoutms=5000, max_items=6)
        t = threading.Thread(
            target=lambda p=pub: [
                p.publish(image=np.zeros((2, 2), np.uint8), frameid=i)
                for i in range(6)
            ],
            daemon=True,
        )
        t.start()
        items = list(stream)
        t.join(timeout=5)
        pub.close()
        traced = [it for it in items if TRACE_KEY in it]
        assert len(traced) == expect, (every, len(traced))
        for it in traced:
            tr = it[TRACE_KEY]
            assert [s[0] for s in tr["stages"]] == ["publish", "recv"]
            assert tr["btid"] == 5
            assert tr["id"].startswith("5-")
    stripped = strip_stamps({TRACE_KEY: {"id": "x"}, "frameid": 1})
    assert TRACE_KEY not in stripped and stripped["frameid"] == 1


def test_trace_batch_helpers_cover_meta_sidecars():
    """stamp/iter/pop reach both the batch-level `_traces` list and
    contexts carried inside `_meta` sidecar dicts (the tile chunk-group
    form), and are cheap no-ops on untraced batches."""
    tr1 = make_trace("a", btid=0, pid=1)
    tr2 = make_trace("b", btid=0, pid=1)
    batch = {
        TRACES_KEY: [tr1],
        "_meta": [{TRACES_KEY: [tr2]}, {"other": 1}],
        "x": np.zeros(2),
    }
    stamp_batch(batch, "decode")
    assert tr1["stages"][-1][0] == "decode"
    assert tr2["stages"][-1][0] == "decode"
    assert {t["id"] for t in iter_traces(batch)} == {"a", "b"}
    out = pop_traces(batch)
    assert {t["id"] for t in out} == {"a", "b"}
    assert TRACES_KEY not in batch
    assert TRACES_KEY not in batch["_meta"][0]
    assert pop_traces({"x": 1}) == []
    assert list(iter_traces({"x": 1})) == []


def test_trace_collector_histograms_report_and_unordered_flag():
    reg = Metrics()
    col = FrameTraceCollector(registry=reg)
    tr = make_trace("f-0", btid=2, pid=4242)
    for s in ("recv", "batch", "step_dispatch", "step_retire"):
        time.sleep(0.001)
        trace_stage(tr, s)
    col.complete(tr)
    rep = col.report()
    assert rep["completed"] == 1 and rep["kept"] == 1
    assert rep["end_to_end"] is True and rep["unordered"] == 0
    for m in ("trace.wire_ms", "trace.queue_ms", "trace.step_ms"):
        assert m in rep["transitions"], rep["transitions"]
        assert reg.histograms()[m]["count"] == 1
        assert rep["transitions"][m]["p50_ms"] >= 0
    # a record whose mono stamps go backwards is flagged, not dropped
    col.complete({
        "id": "u", "btid": 0, "pid": 1,
        "stages": [["publish", 5.0, 5.0], ["recv", 4.0, 5.1]],
    })
    rep = col.report()
    assert rep["unordered"] == 1 and rep["completed"] == 2
    assert reg.counters["trace.unordered"] == 1
    col.reset()
    assert col.report()["completed"] == 0


def test_trace_chrome_events_cross_process_flow_arrows(tmp_path):
    """One completed record renders as stage slices split across the
    producer's pid lane and this process's, bound by an s/f flow pair
    sharing an id on DIFFERENT pids, with both lanes labeled — the
    shape scripts/check_frame_trace.py gates in CI."""
    col = FrameTraceCollector(registry=Metrics())
    tr = make_trace("f-1", btid=3, pid=31337)
    for s in ("recv", "batch", "step_dispatch", "step_retire"):
        trace_stage(tr, s)
    col.complete(tr)
    evs = col.chrome_events()
    starts = [e for e in evs if e["ph"] == "s"]
    fins = [e for e in evs if e["ph"] == "f"]
    assert len(starts) == 1 and len(fins) == 1
    assert starts[0]["id"] == fins[0]["id"]
    assert starts[0]["pid"] == 31337 and fins[0]["pid"] == os.getpid()
    slices = [e for e in evs if e["ph"] == "X"]
    assert len(slices) == 4  # one per stage transition
    assert {e["pid"] for e in slices} == {31337, os.getpid()}
    assert all(e["dur"] >= 0 for e in slices)
    named = {
        e["pid"] for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert {31337, os.getpid()} <= named
    # merged export: chrome_trace(frame_traces=col) carries the lanes
    obj = chrome_trace(events=[], registry=Metrics(), frame_traces=col)
    assert any(e.get("cat") == "frame_trace" for e in obj["traceEvents"])


def test_frame_trace_completes_end_to_end_through_ingest_and_driver():
    """The acceptance path, hermetic: publisher (trace_every=2) ->
    RemoteStream -> HostIngest -> TrainDriver; every sampled frame's
    record reaches step_retire with monotonically ordered stages, the
    trace.* transition histograms land in Metrics.report(), and no
    consumer-visible batch leaks a trace key to the step."""
    from blendjax.data.batcher import HostIngest
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.obs.trace import tracer
    from blendjax.train.driver import TrainDriver
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    tracer.reset()
    pub = DataPublisherSocket(
        WILD, btid=7, telemetry_every=0, trace_every=2
    )
    stream = RemoteStream([pub.addr], timeoutms=5000, max_items=8)
    ingest = HostIngest(stream, batch_size=4).start()
    t = threading.Thread(
        target=lambda: [
            pub.publish(image=np.zeros((2, 2), np.uint8), frameid=i)
            for i in range(8)
        ],
        daemon=True,
    )
    t.start()

    class _Loss:
        def is_ready(self):
            return True

        def __array__(self, dtype=None, copy=None):
            return np.zeros(1, np.float32)

    drv = TrainDriver(
        lambda state, batch: (state, {"loss": _Loss()}),
        state=0, inflight=2, sync_every=0,
    )
    n_batches = 0
    for batch in ingest:
        assert TRACE_KEY not in batch  # popped into _traces by ingest
        drv.submit(batch)
        n_batches += 1
    drv.finish()
    t.join(timeout=5)
    pub.close()
    assert n_batches == 2
    rep = tracer.report()
    assert rep["completed"] == 4  # seq 0, 2, 4, 6
    assert rep["end_to_end"] is True
    assert rep["unordered"] == 0
    for m in ("trace.wire_ms", "trace.queue_ms", "trace.step_ms"):
        assert rep["transitions"][m]["count"] == 4, (m, rep)
    hists = metrics.report()["histograms"]
    assert hists["trace.step_ms"]["count"] == 4
    assert hists["train.step_device_ms"]["count"] == 2
    tracer.reset()


# -- SLO watchdog ------------------------------------------------------------


def test_slo_parse_grammar():
    s = Slo.parse("rate(wire.seq_gaps) == 0")
    assert (s.kind, s.metric, s.op, s.threshold) == (
        "rate", "wire.seq_gaps", "==", 0.0
    )
    q = Slo.parse("p95(wire.e2e_staleness_s) <= 0.5 @ 30")
    assert q.kind == "quantile" and q.quantile == "p95"
    assert q.threshold == 0.5 and q.sustain_s == 30.0
    d = Slo.parse("doctor != wire-bound")
    assert d.kind == "doctor" and d.threshold == "wire-bound"
    g = Slo.parse("train.mfu >= 0.01")  # bare name reads as a gauge
    assert g.kind == "gauge" and g.metric == "train.mfu"
    c = Slo.parse("counter(slo.breach_events) <= 3")
    assert c.kind == "counter"
    with pytest.raises(ValueError):
        Slo.parse("not a rule at all")
    with pytest.raises(ValueError):
        Slo.parse("gauge(train.mfu) >= fast")
    with pytest.raises(ValueError):
        Slo.parse("doctor <= 3")  # verdicts compare with == / != only


def test_watchdog_rate_rule_sustain_window_and_recovery():
    spec = "rate(ingest.items) >= 50 @ 10"
    wd = SloWatchdog([spec])
    # first call: no previous counters, rates have no evidence yet
    r = wd.evaluate({"counters": {"ingest.items": 0}}, now=0.0)
    assert r["healthy"] and r["states"][0]["value"] is None
    # 100 items/s: healthy
    r = wd.evaluate({"counters": {"ingest.items": 1000}}, now=10.0)
    assert r["healthy"] and r["states"][0]["value"] == 100.0
    # starved, but not yet sustained 10s: violating != breached
    r = wd.evaluate({"counters": {"ingest.items": 1000}}, now=20.0)
    assert r["healthy"] and not r["newly_breached"]
    assert r["states"][0]["ok"] is False
    assert r["states"][0]["violating_for_s"] == 0.0
    # still starved 11s later: sustained -> breach
    r = wd.evaluate({"counters": {"ingest.items": 1000}}, now=31.0)
    assert not r["healthy"]
    assert [s["slo"] for s in r["newly_breached"]] == [spec]
    assert wd.breach_events == 1
    assert wd.state()["breached"] == [spec]
    # items flowing again: recovery is reported once
    r = wd.evaluate({"counters": {"ingest.items": 9000}}, now=41.0)
    assert r["healthy"] and r["newly_recovered"] == [spec]
    assert wd.state()["breached"] == []


def test_watchdog_gauge_quantile_doctor_counter_kinds():
    wd = SloWatchdog([
        "gauge(train.mfu) >= 0.1",
        "p95(wire.e2e_staleness_s) <= 0.5",
        "doctor != wire-bound",
        "counter(wire.seq_gaps) == 0",
    ])

    class _V:
        def __init__(self, kind):
            self.kind = kind

    healthy = {
        "gauges": {"train.mfu": 0.2},
        "histograms": {
            "wire.e2e_staleness_s": {"count": 10, "p95": 0.3}
        },
        "counters": {"wire.seq_gaps": 0},
    }
    r = wd.evaluate(healthy, verdict=_V("balanced"), now=1.0)
    assert r["healthy"]
    sick = {
        "gauges": {"train.mfu": 0.01},
        "histograms": {
            "wire.e2e_staleness_s": {"count": 10, "p95": 2.0}
        },
        "counters": {"wire.seq_gaps": 3},
    }
    r = wd.evaluate(sick, verdict=_V("wire-bound"), now=2.0)
    assert not r["healthy"]
    assert sum(1 for s in r["states"] if not s["ok"]) == 4
    # one breach event per newly-breached RULE — the same total the
    # reporter mirrors into the slo.breach_events registry counter
    assert wd.breach_events == 4
    # absent evidence is "no verdict", never a breach — including a
    # rate/counter floor on a counter the pipeline has NOT created yet
    # (slow producer spin-up must not dump a flight record)
    wd2 = SloWatchdog(["gauge(absent) >= 1", "p95(absent) <= 1",
                       "doctor != idle", "rate(absent) >= 50",
                       "counter(absent) >= 1"])
    r = wd2.evaluate({}, verdict=None, now=0.0)
    r = wd2.evaluate({"counters": {}}, verdict=None, now=10.0)
    assert r["healthy"]
    assert all(s["value"] is None for s in r["states"])
    # the moment the counter exists, rate rules bind (created during
    # the window: the delta baselines at 0)
    r = wd2.evaluate({"counters": {"absent": 700}}, verdict=None,
                     now=20.0)
    assert [s for s in r["states"] if s["slo"] == "rate(absent) >= 50"
            ][0]["value"] == 70.0


def test_flight_recorder_bundle_contents_and_pruning(tmp_path):
    reg = Metrics()
    reg.enable_span_events()
    with reg.span("ingest.recv"):
        pass
    col = FrameTraceCollector(registry=reg)
    tr = make_trace("f-9", btid=1, pid=777)
    for s in ("recv", "batch", "step_dispatch", "step_retire"):
        trace_stage(tr, s)
    col.complete(tr)
    fr = FlightRecorder(str(tmp_path), max_bundles=2)
    history = [{"t": 1.0, "doctor": {"kind": "balanced"},
                "report": {"counters": {}}}]
    last = None
    for i in range(4):
        last = fr.dump(
            reason=f"breach-{i}", history=history,
            lineage_report={"1": {"received": 5}},
            slo_states=[{"slo": "rate(x) >= 1", "ok": False}],
            registry=reg, frame_tracer=col,
        )
    bundles = sorted(
        d for d in os.listdir(tmp_path) if d.startswith("flight-")
    )
    assert len(bundles) == 2, bundles  # flapping SLO can't fill disk
    assert os.path.basename(last) == bundles[-1]
    breach = json.load(open(os.path.join(last, "breach.json")))
    assert breach["reason"] == "breach-3"
    assert breach["slo"][0]["slo"] == "rate(x) >= 1"
    snaps = [json.loads(line)
             for line in open(os.path.join(last, "snapshots.jsonl"))]
    assert snaps[0]["doctor"]["kind"] == "balanced"
    lin = json.load(open(os.path.join(last, "lineage.json")))
    assert lin["1"]["received"] == 5
    trace = json.load(open(os.path.join(last, "trace.json")))
    assert any(
        e.get("cat") == "frame_trace" for e in trace["traceEvents"]
    )
    frames = json.load(open(os.path.join(last, "frame_traces.json")))
    assert frames["report"]["completed"] == 1
    assert frames["records"][0]["id"] == "f-9"
    # a restarted process resumes numbering after the surviving
    # bundles instead of overwriting flight-0001 with a new incident
    fr2 = FlightRecorder(str(tmp_path), max_bundles=4)
    again = fr2.dump(reason="after-restart", history=history,
                     registry=reg, frame_tracer=col)
    assert os.path.basename(again) == "flight-0005"


def test_profiler_trace_reentrancy_degrades_to_noop(monkeypatch):
    """A watchdog-triggered capture inside a user's open trace must be
    a logged no-op, not a second jax.profiler.start_trace (which
    raises) — and the guard must reset so later traces still work."""
    import jax

    from blendjax.utils import metrics as um

    calls = {"start": 0, "stop": 0}
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda logdir: calls.__setitem__("start", calls["start"] + 1),
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace",
        lambda: calls.__setitem__("stop", calls["stop"] + 1),
    )
    with um.trace("/tmp/outer"):
        with um.trace("/tmp/nested"):  # degrades, does not raise
            pass
        assert calls == {"start": 1, "stop": 0}
    assert calls == {"start": 1, "stop": 1}
    # the guard cleared: a fresh trace starts the profiler again
    with um.trace("/tmp/later"):
        pass
    assert calls == {"start": 2, "stop": 2}
    # ... and it clears even when start_trace itself raises
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda logdir: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    with pytest.raises(RuntimeError):
        with um.trace("/tmp/broken"):
            pass
    monkeypatch.setattr(
        jax.profiler, "start_trace",
        lambda logdir: calls.__setitem__("start", calls["start"] + 1),
    )
    with um.trace("/tmp/after-failure"):
        pass
    assert calls["stop"] == 3


# -- /healthz + JSONL rotation + concurrent scrape ---------------------------


def _get_status(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def test_healthz_flips_200_503_200_across_breach_and_recovery():
    reg = _filled_registry()
    rep = StatsReporter(
        interval_s=3600, registry=reg, lineage=FrameLineage(),
        slos=["rate(ingest.items) >= 50"],
    )
    srv = start_http_exporter(port=0, registry=reg, health=rep.health)
    url = f"http://127.0.0.1:{srv.port}/healthz"
    try:
        code, body = _get_status(url)  # before any tick: healthy
        assert code == 200 and body["healthy"] is True
        reg.count("ingest.items", 1000)
        rep.tick()  # first tick: rates have no evidence yet
        reg.count("ingest.items", 1000)
        rep.tick()  # plenty of flow
        code, body = _get_status(url)
        assert code == 200 and body["healthy"] is True
        rep.tick()  # starved since last tick -> breach
        code, body = _get_status(url)
        assert code == 503 and body["healthy"] is False
        assert body["slo"]["breached"] == ["rate(ingest.items) >= 50"]
        assert reg.report()["gauges"]["slo.breached"] == 1
        reg.count("ingest.items", 100000)
        rep.tick()  # flow restored -> recovered
        code, body = _get_status(url)
        assert code == 200 and body["healthy"] is True
        assert reg.report()["gauges"]["slo.breached"] == 0
        # /metrics still serves beside /healthz
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5
        ) as resp:
            assert resp.status == 200
    finally:
        srv.close()


def test_healthz_unconfigured_exporter_stays_200():
    srv = start_http_exporter(port=0, registry=_filled_registry())
    try:
        code, body = _get_status(
            f"http://127.0.0.1:{srv.port}/healthz"
        )
        assert code == 200 and body["slo"] == "unconfigured"
    finally:
        srv.close()


def test_http_exporter_concurrent_scrape_while_mutating():
    """Threaded writers churning counters/gauges/histograms/spans while
    repeated GETs hit /metrics: every response must be a 200 whose
    every line parses (a torn snapshot shows up as a garbled line)."""
    reg = Metrics()
    stop = threading.Event()

    def churn(seed):
        i = seed
        while not stop.is_set():
            reg.count("ingest.items")
            reg.gauge(f"g{i % 13}", i)
            reg.observe("scrape.lat", (i % 50) / 1000 + 1e-6)
            with reg.span("scrape.span"):
                pass
            i += 1

    writers = [
        threading.Thread(target=churn, args=(i,), daemon=True)
        for i in range(4)
    ]
    srv = start_http_exporter(port=0, registry=reg)
    url = f"http://127.0.0.1:{srv.port}/metrics"
    try:
        for w in writers:
            w.start()
        for _ in range(25):
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                body = resp.read().decode()
            for line in body.strip().splitlines():
                if line.startswith("#"):
                    assert re.match(
                        r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                        r"(counter|gauge|histogram|summary)$", line
                    ), line
                else:
                    assert _PROM_SAMPLE.match(line), line
    finally:
        stop.set()
        for w in writers:
            w.join(timeout=5)
        srv.close()


def test_jsonl_exporter_rotation_bounds_archive(tmp_path):
    path = str(tmp_path / "run_stats.jsonl")
    ex = JsonlExporter(path, rotate_bytes=4096, keep=3)
    m = _filled_registry()
    for _ in range(200):
        ex.write(m.report())
    files = [path] + [f"{path}.{i}" for i in (1, 2, 3)]
    existing = [f for f in files if os.path.exists(f)]
    assert f"{path}.1" in existing  # rotation actually happened
    assert not os.path.exists(f"{path}.4")  # keep is a hard bound
    # bounded: live file + keep generations, each ~rotate_bytes (+ one
    # line of slack per generation, written before the size check)
    total = sum(os.path.getsize(f) for f in existing)
    assert total <= 4 * (4096 + 2048), total
    # every surviving line, in every generation, still parses
    for f in existing:
        for line in open(f):
            assert json.loads(line)["report"]["counters"]


def test_producer_kill_breach_dumps_flight_bundle_healthz_503(tmp_path):
    """The acceptance scenario, live: a real publisher feeding a real
    ingest; killing the producer starves rate(ingest.items), the
    watchdog breaches on the next tick, the flight recorder writes a
    parseable bundle (snapshots + doctor history + Chrome trace), and
    /healthz serves 503 while breached."""
    from blendjax.data.batcher import HostIngest
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    metrics.enable_span_events()
    flight_dir = str(tmp_path / "flight")
    pub = DataPublisherSocket(
        WILD, btid=9, telemetry_every=0, trace_every=0
    )
    alive = threading.Event()
    alive.set()

    def produce():
        i = 0
        while alive.is_set():
            pub.publish(
                image=np.zeros((2, 2), np.uint8), frameid=i
            )
            i += 1
            time.sleep(0.002)

    producer = threading.Thread(target=produce, daemon=True)
    stream = RemoteStream(
        [pub.addr], timeoutms=250, on_timeout=lambda: True
    )
    ingest = HostIngest(stream, batch_size=4, prefetch=2).start()
    drain_stop = threading.Event()

    def drain():
        for _ in ingest:
            if drain_stop.is_set():
                break

    drainer = threading.Thread(target=drain, daemon=True)
    rep = StatsReporter(
        interval_s=3600,
        slos=["rate(ingest.items) >= 20"],
        flight_dir=flight_dir,
    )
    srv = start_http_exporter(port=0, health=rep.health)
    url = f"http://127.0.0.1:{srv.port}/healthz"
    try:
        producer.start()
        drainer.start()
        time.sleep(0.3)
        rep.tick()  # baseline (rates: no evidence yet) — healthy
        time.sleep(0.3)
        rep.tick()  # live flow, far above the floor — healthy
        assert rep.healthy, rep.watchdog.state()
        assert _get_status(url)[0] == 200
        # kill the producer
        alive.clear()
        producer.join(timeout=5)
        pub.close()
        time.sleep(0.5)  # stragglers drain; then the pipe is dry
        rep.tick()  # starved -> breach -> flight record
        assert not rep.healthy, rep.watchdog.state()
        code, body = _get_status(url)
        assert code == 503
        assert body["slo"]["breached"] == ["rate(ingest.items) >= 20"]
        bundles = sorted(os.listdir(flight_dir))
        assert len(bundles) == 1, bundles
        bundle = os.path.join(flight_dir, bundles[0])
        breach = json.load(
            open(os.path.join(bundle, "breach.json"))
        )
        assert "rate(ingest.items) >= 20" in breach["reason"]
        snaps = [
            json.loads(line)
            for line in open(os.path.join(bundle, "snapshots.jsonl"))
        ]
        # doctor history: the two healthy ticks plus the breach tick
        assert len(snaps) == 3
        assert all(s["doctor"]["kind"] in VERDICTS for s in snaps)
        assert snaps[0]["report"]["counters"]["ingest.items"] > 0
        trace = json.load(open(os.path.join(bundle, "trace.json")))
        assert trace["traceEvents"], "span ring was on; trace is empty"
    finally:
        alive.clear()
        drain_stop.set()
        stream.request_stop()
        srv.close()
        try:
            ingest.stop(timeout=10)
        except Exception:
            pass
        metrics.disable_span_events()
        metrics.reset()
        lineage.reset()


# -- BJX117 regression: watchdog breach state is lock-consistent --------------


def test_watchdog_state_is_safe_against_concurrent_evaluate():
    """The /healthz reader races the reporter thread's evaluate():
    before SloWatchdog grew its RLock, `sorted(self._breached)` could
    throw 'set changed size during iteration' mid-breach-transition."""
    import threading

    from blendjax.obs.watchdog import SloWatchdog

    wd = SloWatchdog(["gauge(x) <= 0.5"])
    stop = threading.Event()
    errors = []

    def hammer():
        try:
            i = 0
            while not stop.is_set():
                # alternate breach on/off so the _breached set churns
                wd.evaluate({"gauges": {"x": float(i % 2)}}, now=float(i))
                i += 1
        except BaseException as e:
            errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(3000):
            s = wd.state()
            assert isinstance(s["breached"], list)
            assert s["healthy"] == (not s["breached"])
            wd.healthy  # the property the fleet controller polls
    finally:
        stop.set()
        t.join(5.0)
    assert not errors, errors
