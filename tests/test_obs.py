"""blendjax.obs: histogram exactness, frame lineage, the stall doctor,
and the exporters (Prometheus / JSONL / Chrome trace)."""

import json
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from blendjax.obs import (
    VERDICTS,
    JsonlExporter,
    StatsReporter,
    chrome_trace,
    diagnose,
    prometheus_text,
    start_http_exporter,
    write_chrome_trace,
)
from blendjax.obs.lineage import (
    PUB_MONO_KEY,
    PUB_WALL_KEY,
    SEQ_KEY,
    FrameLineage,
    strip_stamps,
)
from blendjax.utils.metrics import Histogram, Metrics

WILD = "tcp://127.0.0.1:*"


# -- histograms --------------------------------------------------------------


def test_histogram_quantiles_within_bucket_resolution():
    h = Histogram()
    vals = np.linspace(0.001, 10.0, 5000)
    for v in vals:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 5000
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(10.0)
    # log-bucket midpoint estimate: within ~4.5% relative error
    for q, true in ((0.5, np.quantile(vals, 0.5)),
                    (0.95, np.quantile(vals, 0.95)),
                    (0.99, np.quantile(vals, 0.99))):
        assert h.quantile(q) == pytest.approx(true, rel=0.05)


def test_histogram_nonpositive_values_sort_below_everything():
    h = Histogram()
    for v in (-0.5, 0.0, 1.0, 2.0, 4.0):
        h.observe(v)
    assert h.count == 5
    assert h.zeros == 2
    assert h.quantile(0.0) == -0.5  # exact min preserved
    assert h.quantile(1.0) == 4.0
    assert h.quantile(0.5) == pytest.approx(1.0, rel=0.05)


def test_histogram_exact_counts_under_concurrent_observe():
    """Lock-exactness: N threads x M observes never lose a count, and
    the bucket counts sum exactly to the observe calls."""
    m = Metrics()
    threads_n, per_thread = 8, 2000

    def work(seed):
        rng = np.random.default_rng(seed)
        for _ in range(per_thread):
            m.observe("conc", float(rng.random()) + 1e-6)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(threads_n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    s = m.histograms()["conc"]
    assert s["count"] == threads_n * per_thread
    buckets = m.histogram_buckets()["conc"]
    cum, count, _ = buckets
    assert cum[-1][1] == count == threads_n * per_thread


def test_span_and_histogram_counts_stay_in_lockstep_concurrently():
    """Spans feed same-name histograms under ONE lock acquisition:
    histogram count == span count at any concurrency (the bench
    acceptance check, hermetic version)."""
    m = Metrics()

    def work():
        for _ in range(500):
            with m.span("s"):
                pass

    ts = [threading.Thread(target=work) for _ in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert m.spans()["s"]["count"] == 3000
    assert m.histograms()["s"]["count"] == 3000
    assert "p99_ms" in m.spans()["s"]


def test_report_is_a_consistent_snapshot_under_gauge_churn():
    """gauge()/report() both take the registry lock (the PR-4 fix for
    'dictionary changed size during iteration')."""
    m = Metrics()
    stop = threading.Event()

    def churn():
        i = 0
        while not stop.is_set():
            m.gauge(f"g{i % 997}", i)
            i += 1

    ts = [threading.Thread(target=churn) for _ in range(4)]
    for t in ts:
        t.start()
    try:
        for _ in range(200):
            m.report()  # raced the writers before the lock
    finally:
        stop.set()
        for t in ts:
            t.join()


# -- frame lineage -----------------------------------------------------------


def _stamped(btid, seq, age_s=0.0, **extra):
    return {
        "btid": btid,
        SEQ_KEY: seq,
        PUB_WALL_KEY: time.time() - age_s,
        PUB_MONO_KEY: time.monotonic() - age_s,
        **extra,
    }


def test_lineage_pops_stamps_and_tracks_staleness():
    ln = FrameLineage()
    msg = _stamped(3, 0, age_s=0.5, image=np.zeros(2))
    ln.ingest(msg)
    assert SEQ_KEY not in msg and PUB_WALL_KEY not in msg
    assert "image" in msg  # payload untouched
    rep = ln.report()["3"]
    assert rep["received"] == 1
    assert rep["seq_gaps"] == 0
    assert rep["e2e_staleness_ms"]["p95"] == pytest.approx(500, rel=0.1)
    assert ln.staleness_p95_s() == pytest.approx(0.5, rel=0.1)


def test_lineage_gap_and_reorder_accounting_is_exact():
    ln = FrameLineage()
    for seq in (0, 1, 4, 3, 5):  # drop 2+one-of(3,4)=gap 2, then reorder
        ln.ingest(_stamped(7, seq))
    rep = ln.report()["7"]
    assert rep["seq_gaps"] == 2
    assert rep["seq_reorders"] == 1
    assert rep["last_seq"] == 5
    assert ln.total_gaps() == 2


def test_lineage_producer_respawn_resets_tracking_not_reorder_storm():
    """A respawned producer (launcher reuses the btid, fresh publisher
    numbers from 0) must read as a RESTART: zero reorders, and drop
    detection works immediately in the new incarnation."""
    ln = FrameLineage()
    for seq in range(100):
        ln.ingest(_stamped(5, seq))
    # respawn: seq restarts at 0, then a real drop (skip seq 2)
    for seq in (0, 1, 3, 4):
        ln.ingest(_stamped(5, seq))
    rep = ln.report()["5"]
    assert rep["restarts"] == 1
    assert rep["seq_reorders"] == 0  # no post-respawn reorder storm
    assert rep["seq_gaps"] == 1      # the real drop, flagged at once
    assert rep["last_seq"] == 4


def test_lineage_interleaved_producers_are_not_gaps():
    """Round-robin interleave of independent producers (what the
    sharded pool's fan-in looks like) must count ZERO gaps: tracking is
    per producer."""
    ln = FrameLineage()
    for seq in range(20):
        for btid in (0, 1, 2):
            ln.ingest(_stamped(btid, seq))
    assert ln.total_gaps() == 0
    for btid in ("0", "1", "2"):
        assert ln.report()[btid]["seq_gaps"] == 0


def test_lineage_unstamped_messages_pass_through():
    ln = FrameLineage()
    msg = {"btid": 0, "image": np.zeros(2)}
    ln.ingest(msg)
    assert ln.report() == {}


def test_lineage_telemetry_fleet_view():
    ln = FrameLineage()
    ln.ingest(_stamped(0, 0, _telemetry={"seq": 0, "mps": 12.5,
                                         "spans": {}, "counters": {}}))
    rep = ln.report()["0"]
    assert rep["telemetry"]["mps"] == 12.5
    assert rep["telemetry_age_s"] >= 0.0


def test_strip_stamps_for_replay():
    msg = _stamped(0, 3, _telemetry={})
    out = strip_stamps(msg)
    assert out is msg
    assert set(msg) == {"btid"}


# -- stamps over a real socket ----------------------------------------------


def test_publisher_stamps_and_stream_accounts_them():
    """DataPublisherSocket stamps -> RemoteStream pops + accounts; the
    consumer-visible items carry NO stamp keys, and the process-wide
    lineage sees exact per-producer sequence accounting."""
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pub = DataPublisherSocket(WILD, btid=11, telemetry_every=2)
    stream = RemoteStream([pub.addr], timeoutms=5000, max_items=5)
    t = threading.Thread(
        target=lambda: [
            pub.publish(image=np.zeros((4, 4), np.uint8), frameid=i)
            for i in range(5)
        ],
        daemon=True,
    )
    t.start()
    items = list(stream)
    t.join(timeout=5)
    pub.close()
    assert len(items) == 5
    for it in items:
        assert SEQ_KEY not in it and PUB_WALL_KEY not in it
        assert "_telemetry" not in it
    rep = lineage.report()["11"]
    assert rep["received"] == 5
    assert rep["last_seq"] == 4
    assert rep["seq_gaps"] == 0
    # telemetry_every=2: snapshots piggybacked on seq 0/2/4 — latest won
    assert rep["telemetry"]["seq"] in (2, 4)
    assert metrics.counters.get("wire.seq_gaps", 0) == 0
    assert metrics.histograms()["wire.e2e_staleness_s"]["count"] == 5


def test_sharded_ingest_partitions_do_not_fake_gaps_but_real_gaps_flag():
    """Two producers partitioned across two shard workers: the
    round-robin interleave counts zero gaps; a producer that SKIPS a
    seq (simulated drop) is flagged with the exact gap size."""
    from blendjax.data.shard_ingest import ShardedHostIngest
    from blendjax.data.stream import RemoteStream, partition_addresses
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pubs = [
        DataPublisherSocket(WILD, btid=i, telemetry_every=0)
        for i in range(2)
    ]
    n = 8

    def feed(pub, skip=None):
        for i in range(n):
            if i == skip:
                pub._seq += 1  # simulate a dropped message: seq skips
                continue
            pub.publish(image=np.full((2, 2), pub.btid, np.uint8),
                        frameid=i)

    shards = partition_addresses([p.addr for p in pubs], 2)
    assert len(shards) == 2
    streams = [
        # track_gaps=True: shards see DISJOINT producer subsets, so gap
        # accounting is sound despite the worker slot (what the
        # pipeline's shard_stream passes).
        RemoteStream(s, timeoutms=5000, worker_index=i, num_workers=2,
                     track_gaps=True)
        for i, s in enumerate(shards)
    ]
    ingest = ShardedHostIngest(
        streams, batch_size=2, max_messages=2 * n - 1
    )
    threads = [
        threading.Thread(target=feed, args=(pubs[0],), daemon=True),
        threading.Thread(target=feed, args=(pubs[1], 3), daemon=True),
    ]
    for t in threads:
        t.start()
    batches = list(ingest)
    for t in threads:
        t.join(timeout=5)
    for p in pubs:
        p.close()
    assert sum(len(b["_meta"]) for b in batches) >= 2 * n - 4
    rep = lineage.report()
    assert rep["0"]["seq_gaps"] == 0  # clean producer: no false gaps
    assert rep["1"]["seq_gaps"] == 1  # the simulated drop, exactly
    assert metrics.counters.get("wire.seq_gaps", 0) == 1


def test_shared_fanin_consumers_do_not_fake_gaps():
    """Two consumers splitting ONE producer fan-in (DataLoader-worker
    shape: same addresses, num_workers=2) each see a strided
    subsequence — the auto track_gaps default must count ZERO gaps,
    while staleness accounting stays on."""
    from blendjax.data.stream import RemoteStream
    from blendjax.obs.lineage import lineage
    from blendjax.transport import DataPublisherSocket
    from blendjax.utils.metrics import metrics

    metrics.reset()
    lineage.reset()
    pub = DataPublisherSocket(WILD, btid=4, telemetry_every=0)
    streams = [
        RemoteStream([pub.addr], timeoutms=5000, worker_index=i,
                     num_workers=2, max_items=16)
        for i in range(2)
    ]
    assert all(not s.track_gaps for s in streams)

    def drain(s, out):
        out.extend(s)

    outs: list = [[], []]
    ts = [
        threading.Thread(target=drain, args=(s, o), daemon=True)
        for s, o in zip(streams, outs)
    ]
    for t in ts:
        t.start()
    time.sleep(0.3)  # both PULL peers connected before publishing
    for i in range(16):
        pub.publish(image=np.zeros((2, 2), np.uint8), frameid=i)
    for t in ts:
        t.join(timeout=10)
    pub.close()
    assert sum(len(o) for o in outs) == 16
    rep = lineage.report()["4"]
    assert rep["seq_gaps"] == 0 and rep["seq_reorders"] == 0
    assert rep["e2e_staleness_ms"]["count"] == 16  # staleness stays on
    assert metrics.counters.get("wire.seq_gaps", 0) == 0


def test_nonfinite_staleness_stamp_does_not_kill_ingest():
    """A corrupted producer clock (NaN/inf _pub_wall) must not raise
    out of lineage.ingest and kill the receive loop."""
    ln = FrameLineage()
    for wall in (float("nan"), float("inf"), float("-inf")):
        ln.ingest({"btid": 9, SEQ_KEY: 0, PUB_WALL_KEY: wall,
                   PUB_MONO_KEY: 0.0})  # staleness = now - wall = ±inf/nan
    h = Histogram()
    h.observe(float("nan"))
    h.observe(float("inf"))
    h.observe(1.0)
    s = h.summary()
    assert s["count"] == 1  # finite sample only
    assert s["nonfinite"] == 2
    assert h.quantile(0.5) == 1.0


# -- stall doctor ------------------------------------------------------------


def _report(spans=None, counters=None, gauges=None):
    return {
        "spans": {
            k: {"count": 10, "total_s": v} for k, v in (spans or {}).items()
        },
        "counters": counters or {},
        "gauges": gauges or {},
        "histograms": {},
    }


def test_doctor_step_bound_on_backpressure():
    v = diagnose(_report(
        spans={"ingest.recv": 1.0, "ingest.queue_wait": 0.1,
               "train.dispatch": 8.0},
        counters={"ingest.queue_full_waits": 40},
    ))
    assert v.kind == "step-bound"
    assert "queue_full_waits=40" in v.reason


def test_doctor_step_bound_on_driver_ring():
    v = diagnose(
        _report(spans={"train.dispatch": 1.0, "driver.ring_wait": 4.0}),
        driver={"host_blocks": 25},
    )
    assert v.kind == "step-bound"


def test_doctor_feed_bound():
    v = diagnose(_report(
        spans={"feed.throttle_wait": 5.0, "feed.place": 1.0,
               "train.dispatch": 2.0},
        counters={"feed.throttle_blocks": 17},
    ))
    assert v.kind == "feed-bound"
    assert "throttle_blocks=17" in v.reason


def test_doctor_decode_bound():
    v = diagnose(_report(
        spans={"decode.dispatch": 6.0, "train.dispatch": 2.0,
               "ingest.queue_wait": 1.0},
    ))
    assert v.kind == "decode-bound"


def test_doctor_step_bound_on_pinned_queue_depth_gauge():
    """queue_depth_hwm pinned at the prefetch bound is backpressure
    evidence even before a queue_full_wait is ever counted."""
    v = diagnose(
        _report(
            spans={"train.dispatch": 5.0, "ingest.queue_wait": 0.1},
            gauges={"ingest.queue_depth_hwm": 2},
        ),
        prefetch=2,
    )
    assert v.kind == "step-bound"
    assert "queue_depth_hwm=2" in v.reason


def test_doctor_sharded_recv_time_does_not_fake_starvation():
    """N shard workers parked in recv bank ~N x wall of ingest.recv*
    span time concurrently; that must not classify a healthy run as
    starving when the consumer itself never waits on the queue."""
    v = diagnose(_report(spans={
        "ingest.recv.shard0": 2.0, "ingest.recv.shard1": 2.0,
        "ingest.recv.shard2": 2.0, "ingest.recv.shard3": 2.0,
        "ingest.queue_wait": 0.1, "train.dispatch": 2.0,
        "feed.place": 1.0,
    }))
    assert v.kind == "balanced"


def test_doctor_wire_vs_producer_bound_split_on_staleness():
    starving = _report(
        spans={"ingest.queue_wait": 6.0, "ingest.recv": 2.0,
               "train.dispatch": 1.0},
    )
    stale_lineage = {
        "0": {"e2e_staleness_ms": {"count": 50, "p95": 900.0}},
    }
    fresh_lineage = {
        "0": {"e2e_staleness_ms": {"count": 50, "p95": 8.0}},
    }
    assert diagnose(starving, lineage=stale_lineage).kind == "wire-bound"
    assert diagnose(starving, lineage=fresh_lineage).kind == "producer-bound"
    # no lineage at all: still a verdict (producer-bound, "unstamped")
    v = diagnose(starving)
    assert v.kind == "producer-bound"
    assert "unstamped" in v.reason


def test_doctor_balanced_and_idle_and_render_shape():
    assert diagnose(_report()).kind == "idle"
    v = diagnose(_report(spans={
        "ingest.recv": 1.0, "ingest.queue_wait": 1.0, "feed.place": 1.0,
        "decode.dispatch": 1.0, "train.dispatch": 1.0,
    }))
    assert v.kind == "balanced"
    line = v.render()
    assert line.startswith("doctor: balanced — ") and "\n" not in line
    assert all(k in VERDICTS for k in (
        "step-bound", "feed-bound", "decode-bound", "wire-bound",
        "producer-bound", v.kind, "idle",
    ))


# -- exporters ---------------------------------------------------------------

_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"[-+]?[0-9]+(\.[0-9]+)?([eE][-+]?[0-9]+)?$"
)


def _filled_registry():
    m = Metrics()
    m.count("wire.raw_bytes", 1024)
    m.count("ingest.items", 7)
    m.gauge("ingest.queue_depth", 2)
    for v in (0.001, 0.002, 0.004, 0.02):
        m.observe("ingest.recv", v)
    with m.span("feed.place"):
        pass
    return m


def test_prometheus_text_is_well_formed():
    m = _filled_registry()
    lineage_report = {
        str(b): {"received": 7, "seq_gaps": 0, "seq_reorders": 0,
                 "restarts": 0,
                 "e2e_staleness_ms": {"count": 7, "p50": 3.0, "p95": 9.0,
                                      "p99": 12.0}}
        for b in (0, 1)
    }
    text = prometheus_text(report=m.report(), lineage_report=lineage_report,
                           registry=m)
    assert text.endswith("\n")
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram|summary)$", line), line
        else:
            assert _PROM_SAMPLE.match(line), line
    # exposition grouping: all samples of one metric name are ONE
    # contiguous block (multi-producer pages are rejected by strict
    # parsers otherwise)
    names, last = [], None
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name = line.split("{", 1)[0].split(" ", 1)[0]
        if name != last:
            names.append(name)
            last = name
    assert len(names) == len(set(names)), names
    # histogram invariants: cumulative buckets monotone, +Inf == count
    assert 'blendjax_ingest_recv_bucket{le="+Inf"} 4' in text
    cums = [
        int(line.rsplit(" ", 1)[1])
        for line in text.splitlines()
        if line.startswith("blendjax_ingest_recv_bucket")
    ]
    assert cums == sorted(cums)
    assert "blendjax_wire_raw_bytes_total 1024" in text
    assert 'blendjax_producer_e2e_staleness_ms{btid="0",quantile="0.95"} 9.0' in text
    assert 'blendjax_producer_seq_gaps_total{btid="1"} 0' in text


def test_http_exporter_serves_live_registry():
    m = _filled_registry()
    srv = start_http_exporter(port=0, registry=m)
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain")
            body = resp.read().decode()
        assert "blendjax_ingest_items_total 7" in body
        # a second scrape sees fresh state
        m.count("ingest.items", 1)
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert "blendjax_ingest_items_total 8" in resp.read().decode()
    finally:
        srv.close()


def test_jsonl_exporter_appends_parseable_lines(tmp_path):
    path = str(tmp_path / "snapshots.jsonl")
    ex = JsonlExporter(path)
    m = _filled_registry()
    ex.write(m.report())
    ex.write(m.report(), extra={"doctor": {"kind": "balanced"}})
    lines = open(path).read().strip().splitlines()
    assert len(lines) == 2
    for line in lines:
        rec = json.loads(line)
        assert rec["t"] > 0
        assert rec["report"]["counters"]["ingest.items"] == 7
    assert json.loads(lines[1])["doctor"]["kind"] == "balanced"


def test_chrome_trace_export_well_formed(tmp_path):
    m = Metrics()
    m.enable_span_events()
    with m.span("ingest.recv"):
        time.sleep(0.001)
    with m.span("feed.place"):
        pass
    obj = chrome_trace(registry=m)
    assert set(obj) == {"traceEvents", "displayTimeUnit"}
    assert len(obj["traceEvents"]) == 2
    for ev in obj["traceEvents"]:
        assert ev["ph"] == "X"
        assert set(ev) >= {"name", "cat", "ts", "dur", "pid", "tid"}
        assert ev["dur"] >= 0
    path = str(tmp_path / "trace.json")
    assert write_chrome_trace(path, registry=m) == 2
    loaded = json.load(open(path))
    assert loaded["traceEvents"][0]["name"] in ("ingest.recv", "feed.place")
    # events ring respects capacity and disable
    m.disable_span_events()
    with m.span("x"):
        pass
    assert len(m.span_events()) == 0


# -- stats reporter ----------------------------------------------------------


def test_stats_reporter_tick_logs_verdict_and_archives(tmp_path):
    m = _filled_registry()
    ln = FrameLineage()
    path = str(tmp_path / "stats.jsonl")
    rep = StatsReporter(
        interval_s=3600, registry=m, lineage=ln, jsonl_path=path,
        driver_stats=lambda: {"host_blocks": 0},
    )
    v = rep.tick()
    assert v.kind in VERDICTS
    assert rep.last_verdict is v
    rec = json.loads(open(path).read().strip())
    assert rec["doctor"]["kind"] == v.kind
    assert "lineage" in rec


def test_stats_reporter_thread_lifecycle(tmp_path):
    m = _filled_registry()
    rep = StatsReporter(interval_s=0.05, registry=m,
                        lineage=FrameLineage())
    rep.start()
    time.sleep(0.2)
    rep.stop()
    assert rep.last_verdict is not None
