"""Mesh/sharding/collectives/ring-attention on the 8-device CPU mesh."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from blendjax.parallel import (  # noqa: E402
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    batch_sharding,
    create_mesh,
    param_sharding_rules,
    replicated,
    ring_attention,
    ring_permute,
    shard_params,
)
from blendjax.parallel.mesh import MeshSpec  # noqa: E402
from blendjax.parallel.ring import reference_attention  # noqa: E402


def test_mesh_spec_resolution():
    assert MeshSpec({"data": -1}).resolve(8) == {"data": 8}
    assert MeshSpec({"data": -1, "tensor": 2}).resolve(8) == {
        "data": 4, "tensor": 2
    }
    assert MeshSpec({"data": 2, "seq": 4}).resolve(8) == {"data": 2, "seq": 4}
    with pytest.raises(AssertionError):
        MeshSpec({"data": 3}).resolve(8)


def test_create_mesh_axes():
    mesh = create_mesh({"data": -1, "tensor": 2})
    assert mesh.axis_names == ("data", "tensor")
    assert mesh.shape == {"data": 4, "tensor": 2}


def test_batch_and_replicated_sharding():
    mesh = create_mesh({"data": 4, "fsdp": 2})
    s = batch_sharding(mesh)
    assert s.spec == P(("data", "fsdp"))
    assert replicated(mesh).spec == P()


def test_param_sharding_rules():
    mesh = create_mesh({"fsdp": 4, "tensor": 2})
    dense = np.zeros((256, 128))
    s = param_sharding_rules(mesh, ("dense", "kernel"), dense)
    assert s.spec[-1] == "tensor" and "fsdp" in s.spec
    bias = np.zeros((7,))
    assert param_sharding_rules(mesh, ("b",), bias).spec == P()
    params = {"w": dense, "b": bias}
    placed = shard_params(mesh, params)
    assert placed["w"].sharding.spec[-1] == "tensor"


def test_collectives_sum_mean_gather_permute():
    mesh = create_mesh({"data": 8})
    x = jnp.arange(8.0)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    np.testing.assert_allclose(all_reduce_sum(xs, mesh), np.full(1, 28.0))
    np.testing.assert_allclose(all_reduce_mean(xs, mesh), np.full(1, 3.5))
    g = all_gather(xs, mesh)
    np.testing.assert_allclose(np.asarray(g), np.arange(8.0))

    mesh2 = create_mesh({"seq": 8})
    y = jax.device_put(jnp.arange(8.0), NamedSharding(mesh2, P("seq")))
    rolled = ring_permute(y, mesh2, axis="seq", shift=1)
    np.testing.assert_allclose(np.asarray(rolled), np.roll(np.arange(8.0), 1))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = create_mesh({"seq": 8})
    rng = np.random.default_rng(0)
    b, t, h, d = 2, 32, 2, 4
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    spec = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, axis="seq", causal=causal,
                         batch_axis=None)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # output stays sequence-sharded on the ring
    assert out.sharding.spec == P(None, "seq")


def test_reference_attention_bf16_inputs_keep_f32_accumulation():
    """bf16 inputs must accumulate scores and softmax in f32
    (``preferred_element_type``): dropping that roughly doubles the
    error (calibrated at T=512: f32-accum 3.2e-3 vs bf16-accum 6.9e-3
    on CPU; 1.5e-3 vs 5.7e-3 on the v5e) while every other test — all
    f32 inputs — would keep passing. The 4.5e-3 bar sits between the
    regimes on both backends."""
    k = jax.random.key(0)
    b, t, h, d = 2, 512, 4, 64
    q = jax.random.normal(k, (b, t, h, d), jnp.float32)
    kk = jax.random.normal(jax.random.key(1), (b, t, h, d), jnp.float32)
    v = jax.random.normal(jax.random.key(2), (b, t, h, d), jnp.float32)
    ref = np.asarray(reference_attention(q, kk, v))
    got = np.asarray(
        reference_attention(
            q.astype(jnp.bfloat16), kk.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
        ).astype(jnp.float32)
    )
    assert got.dtype == np.float32 and ref.shape == got.shape
    assert np.max(np.abs(ref - got)) < 4.5e-3


def test_ring_attention_bf16_inputs_ring_exactly():
    """bf16 in, bf16 out, matching the f32 reference within bf16
    tolerance: the ring body accumulates scores and streaming-softmax
    carries in f32 (``preferred_element_type``) while the K/V blocks
    themselves stay bf16 on the wire."""
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    rng = np.random.default_rng(5)
    b, t, h, d = 2, 64, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    spec = NamedSharding(mesh, P(None, "seq"))
    out = ring_attention(
        *(jax.device_put(x, spec) for x in (qb, kb, vb)),
        mesh, axis="seq", batch_axis=None,
    )
    assert out.dtype == jnp.bfloat16
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out.astype(jnp.float32)), np.asarray(ref), atol=2e-2
    )


def test_ring_attention_bf16_halves_ppermute_bytes():
    """The ROADMAP item 5 fix pinned structurally: bf16 q/k/v must
    enter ``shard_map`` unconverted, so every ``ppermute`` rotates
    bf16 K/V blocks — the old pre-shard_map f32 upcast doubled the
    bytes each ICI hop moved. The jaxpr is the proof: with bf16 inputs
    no ppermute may carry an f32 operand."""
    mesh = create_mesh({"seq": 4}, devices=jax.devices()[:4])
    b, t, h, d = 1, 32, 2, 8
    qb = jnp.zeros((b, t, h, d), jnp.bfloat16)
    jaxpr = jax.make_jaxpr(
        lambda q, k, v: ring_attention(
            q, k, v, mesh, axis="seq", batch_axis=None
        )
    )(qb, qb, qb)
    perms = [
        eqn
        for eqn in jaxpr.jaxpr.eqns
        for inner in [eqn]
        if inner.primitive.name == "ppermute"
    ] + [
        eqn
        for outer in jaxpr.jaxpr.eqns
        if "jaxpr" in outer.params or "call_jaxpr" in outer.params
        for eqn in _walk_eqns(outer)
        if eqn.primitive.name == "ppermute"
    ]
    assert perms, "no ppermute in the ring jaxpr?"
    for eqn in perms:
        for var in eqn.invars:
            assert str(var.aval.dtype) == "bfloat16", (
                f"ppermute carries {var.aval.dtype}: the f32 upcast "
                "is back in front of shard_map"
            )


def _walk_eqns(eqn):
    """All equations reachable through an eqn's sub-jaxprs (shard_map /
    scan / fori bodies), recursively."""
    out = []
    for v in eqn.params.values():
        inner = getattr(v, "jaxpr", v)
        for e in getattr(inner, "eqns", ()):
            out.append(e)
            out.extend(_walk_eqns(e))
    return out


def test_ring_attention_with_data_and_seq_axes():
    mesh = create_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(1)
    b, t, h, d = 4, 16, 2, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    spec = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ring_attention(qs, ks, vs, mesh, axis="seq", causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_reference(causal):
    from blendjax.parallel import ulysses_attention

    mesh = create_mesh({"seq": 8})
    rng = np.random.default_rng(2)
    b, t, h, d = 2, 32, 8, 4  # h divisible by the 8-way seq axis
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    spec = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))
    out = ulysses_attention(qs, ks, vs, mesh, axis="seq", causal=causal,
                            batch_axis=None)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # output comes back sequence-sharded (same contract as ring)
    assert out.sharding.spec == P(None, "seq")


def test_ulysses_attention_with_data_axis_and_jit():
    from blendjax.parallel import ulysses_attention

    mesh = create_mesh({"data": 2, "seq": 4})
    rng = np.random.default_rng(3)
    b, t, h, d = 4, 16, 4, 8
    q, k, v = (
        jnp.asarray(rng.normal(size=(b, t, h, d)).astype(np.float32))
        for _ in range(3)
    )
    spec = NamedSharding(mesh, P("data", "seq"))
    qs, ks, vs = (jax.device_put(x, spec) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ulysses_attention(q, k, v, mesh, axis="seq", causal=True)

    out = f(qs, ks, vs)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_attention_head_divisibility_guard():
    from blendjax.parallel import ulysses_attention

    mesh = create_mesh({"seq": 8})
    x = jnp.zeros((1, 16, 4, 8))  # 4 heads not divisible by 8-way seq
    with pytest.raises(AssertionError, match="divisible"):
        ulysses_attention(x, x, x, mesh, axis="seq")


def test_streamformer_ulysses_grad_step():
    """StreamFormer with sp_mode='ulysses' takes a finite grad step on a
    dp x seq mesh."""
    from blendjax.models import StreamFormer
    from blendjax.parallel import batch_sharding

    mesh = create_mesh({"data": 2, "seq": 4})
    model = StreamFormer(
        patch=8, dim=32, depth=1, num_heads=4, num_outputs=16,
        use_ring=True, sp_mode="ulysses", mesh=mesh,
    )
    images = np.zeros((4, 32, 32, 4), np.uint8)
    params = model.init(jax.random.key(0), images)["params"]
    imgs = jax.device_put(jnp.asarray(images), batch_sharding(mesh))

    @jax.jit
    def loss_grad(p, x):
        def loss(p):
            return jnp.mean(model.apply({"params": p}, x) ** 2)

        return jax.value_and_grad(loss)(p)

    loss, grads = loss_grad(params, imgs)
    assert np.isfinite(float(loss))
    leaf = jax.tree_util.tree_leaves(grads)[0]
    assert np.all(np.isfinite(np.asarray(leaf)))


# -- layouts, partition rules, and the batch-layout gate ---------------------
# (the layout system: Layout/resolve_layout compose data×fsdp×tp meshes,
# PartitionRule trees shard params AND optimizer moments, and
# validate_batch_sharding keeps model axes out of batch leading dims)


def _layout_api():
    from blendjax.parallel import (
        DEFAULT_TP_RULES,
        Layout,
        PartitionRule,
        resolve_layout,
        resolve_rules,
        state_resident_bytes,
        state_shardings,
        validate_batch_sharding,
    )

    return (DEFAULT_TP_RULES, Layout, PartitionRule, resolve_layout,
            resolve_rules, state_resident_bytes, state_shardings,
            validate_batch_sharding)


def test_layout_resolution_and_mesh():
    _, Layout, _, resolve_layout, *_ = _layout_api()
    assert resolve_layout(None).mesh_axes() == {"data": -1}
    assert resolve_layout("data2xfsdp4").mesh_axes() == {
        "data": 2, "fsdp": 4
    }
    # sizeless model axes split 2-way; data absorbs the rest
    assert resolve_layout("data×fsdp×tp").mesh_axes() == {
        "data": -1, "fsdp": 2, "tp": 2
    }
    assert resolve_layout({"data": 4, "tp": 2}).mesh_axes() == {
        "data": 4, "tp": 2
    }
    lo = Layout(name="custom", fsdp=4)
    assert resolve_layout(lo) is lo
    with pytest.raises(ValueError, match="warp"):
        resolve_layout("data×warp")
    mesh = resolve_layout("data4xtp2").create_mesh()
    assert dict(mesh.shape) == {"data": 4, "tp": 2}


def test_partition_rule_first_match_and_missing_axis_drop():
    _, _, PartitionRule, *_ = _layout_api()
    rules = (PartitionRule(r"qkv/kernel$", ("tp", None)),)
    mesh = create_mesh({"data": 4, "tp": 2})
    s = param_sharding_rules(
        mesh, ("blk", "qkv", "kernel"), np.zeros((8, 6)), rules=rules
    )
    assert "tp" in tuple(s.spec)
    # an axis the mesh doesn't carry is dropped, not an error: the
    # same rule set works on a pure-data mesh
    s2 = param_sharding_rules(
        create_mesh({"data": 8}), ("blk", "qkv", "kernel"),
        np.zeros((8, 6)), rules=rules,
    )
    assert all(a != "tp" for a in tuple(s2.spec or ()))


def test_resolve_rules_precedence():
    (_, Layout, PartitionRule, _, resolve_rules, *_) = _layout_api()

    class WithRules:
        def partition_rules(self):
            return (PartitionRule(r"^w$", ("tp",)),)

    model = WithRules()
    assert resolve_rules(model=model) == model.partition_rules()
    explicit = (PartitionRule(r"^w$", (None, "tp")),)
    assert resolve_rules(rules=explicit, model=model) == explicit
    lo = Layout(tp=2, rules=(PartitionRule(r"^v$", ("tp",)),))
    assert resolve_rules(layout=lo, model=model) == lo.rules
    assert resolve_rules() == ()


def test_state_shardings_rules_cover_optimizer_moments():
    """The one-helper contract: deriving shardings from rules over the
    state tree reproduces the committed placement leaf for leaf —
    optimizer moments included (their paths mirror param paths)."""
    from blendjax.models import CubeRegressor
    from blendjax.train import make_train_state

    (*_, state_shardings, _) = _layout_api()
    mesh = create_mesh({"data": 2, "fsdp": 4})
    state = make_train_state(
        CubeRegressor(features=(8,), dtype=jnp.float32),
        np.zeros((8, 16, 16, 4), np.uint8),
        mesh=mesh, layout="data2xfsdp4",
    )
    derived = state_shardings(state, mesh=mesh, layout="data2xfsdp4")
    got = {
        jax.tree_util.keystr(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(derived)[0]
    }
    fsdp_leaves = 0
    checked = 0
    for p, w in jax.tree_util.tree_flatten_with_path(state)[0]:
        if not hasattr(w, "sharding"):  # the plain-int step counter
            continue
        d = got[jax.tree_util.keystr(p)]
        assert d.spec == w.sharding.spec, jax.tree_util.keystr(p)
        checked += 1
        if "fsdp" in jax.tree_util.tree_leaves(tuple(d.spec)):
            fsdp_leaves += 1
    assert checked >= 12
    # params AND both adam moments carry fsdp shards (>= 3 trees' worth)
    assert fsdp_leaves >= 3


def test_validate_batch_sharding_gate():
    (*_, validate_batch_sharding) = _layout_api()
    mesh = create_mesh({"data": 2, "fsdp": 2, "tp": 2})
    # the two batch layouts: data alone, and the fsdp fold
    validate_batch_sharding(NamedSharding(mesh, P("data")))
    validate_batch_sharding(NamedSharding(mesh, P(("data", "fsdp"))))
    with pytest.raises(ValueError, match="tp"):
        validate_batch_sharding(NamedSharding(mesh, P("tp")))
    with pytest.raises(ValueError, match="fsdp"):
        # fsdp shards *state*, never the batch on its own
        validate_batch_sharding(NamedSharding(mesh, P("fsdp")))
    with pytest.raises(ValueError, match="tp"):
        # model axes may not appear on inner batch dims either
        validate_batch_sharding(NamedSharding(mesh, P("data", "tp")))


def test_fsdp_state_resident_bytes_shrink():
    from blendjax.models import CubeRegressor
    from blendjax.train import make_train_state

    (*_, state_resident_bytes, _, _) = _layout_api()
    img = np.zeros((8, 16, 16, 4), np.uint8)
    model = CubeRegressor(features=(8,), dtype=jnp.float32)
    rep = make_train_state(model, img, mesh=create_mesh({"data": 8}))
    fsdp = make_train_state(
        model, img, mesh=create_mesh({"data": 2, "fsdp": 4}),
        layout="data2xfsdp4",
    )
    ratio = state_resident_bytes(rep) / state_resident_bytes(fsdp)
    # ~|fsdp| = 4, minus slack for replicated biases/scalars
    assert ratio > 3
