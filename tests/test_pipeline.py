"""Device-feeding pipeline on the virtual 8-device CPU mesh: the full
ingest path (producers -> sockets -> batches -> sharded global arrays),
i.e. the blendjax replacement for DataLoader+collate+.cuda()."""

import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from blendjax.data import DeviceFeeder, StreamDataPipeline  # noqa: E402

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen", "cube_producer.py"
)


def _data_sharding():
    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    return mesh, NamedSharding(mesh, P("data"))


def test_device_feeder_shards_batch_on_mesh():
    mesh, sharding = _data_sharding()
    batches = [
        {
            "image": np.full((8, 4, 4, 4), i, np.uint8),
            "frameid": np.arange(8),
            "_meta": [{"btid": 0}] * 8,
        }
        for i in range(4)
    ]
    feeder = DeviceFeeder(sharding=sharding, prefetch=2)
    out = list(feeder(batches))
    assert len(out) == 4
    for i, b in enumerate(out):
        assert isinstance(b["image"], jax.Array)
        assert b["image"].sharding == sharding
        # batch axis split across the 8 devices: one item per device
        shard_shapes = {s.data.shape for s in b["image"].addressable_shards}
        assert shard_shapes == {(1, 4, 4, 4)}
        assert b["_meta"][0]["btid"] == 0  # metadata stays host-side
        np.testing.assert_array_equal(np.asarray(b["frameid"]), np.arange(8))


def test_stream_pipeline_end_to_end_with_producers():
    from blendjax.launcher import PythonProducerLauncher

    mesh, sharding = _data_sharding()
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA"],
        seed=1,
        instance_args=[["--shape", "32", "32"]] * 2,
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=8,
            sharding=sharding,
            timeoutms=20000,
        ) as pipe:
            it = iter(pipe)
            seen_btids = set()
            # Producers start at different times on a loaded host (a
            # fast first producer can feed MANY batches before the
            # second finishes importing), so the fan-in wait is TIME
            # bounded, not batch-count bounded.
            deadline = time.time() + 30
            i = 0
            while time.time() < deadline:
                batch = next(it)
                assert batch["image"].shape == (8, 32, 32, 4)
                assert batch["image"].sharding == sharding
                assert batch["image"].dtype == np.uint8
                seen_btids |= {m.get("btid") for m in batch["_meta"]}
                if i >= 3 and seen_btids == {0, 1}:
                    break
                i += 1
            assert pipe.queue_depth() >= 0
    assert seen_btids == {0, 1}


def test_batched_producer_end_to_end_and_tail_flush():
    """--batch mode: producer publishes (B, ...) messages; a --frames count
    that is not a multiple of --batch still delivers every frame (the tail
    partial batch is flushed at shutdown and re-batched by ingest)."""
    from blendjax.data import RemoteStream
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=1,
        instance_args=[["--shape", "32", "32", "--batch", "4", "--frames", "10"]],
    ) as launcher:
        stream = RemoteStream(
            launcher.addresses["DATA"], timeoutms=40000, max_items=3
        )
        frames = []
        for msg in stream:
            assert msg["_batched"] is True
            frames.extend(msg["frameid"].tolist())
        assert sorted(frames) == list(range(1, 11))


def test_device_feeder_multihost_assembles_global_batch():
    """multihost=True routes through jax.make_array_from_process_local_data
    (degenerate single-process case here: local data == global batch);
    the result is a global array under the requested sharding."""
    mesh, sharding = _data_sharding()
    batches = [
        {
            "image": np.arange(8 * 4 * 4 * 4, dtype=np.uint8).reshape(
                8, 4, 4, 4
            ),
            "frameid": np.arange(8),
        }
    ]
    feeder = DeviceFeeder(sharding=sharding, prefetch=1, multihost=True)
    (out,) = list(feeder(batches))
    assert out["image"].sharding.is_equivalent_to(sharding, 4)
    np.testing.assert_array_equal(np.asarray(out["image"]), batches[0]["image"])


# -- deferred run-length decode + placement levers ---------------------------


def _ndr_messages(n, batch=4, h=32, w=32, deferred=True):
    from blendjax.transport.wire import (
        WireCompressState,
        decode_message,
        encode_message,
    )

    state = WireCompressState()
    out = []
    for i in range(n):
        img = np.zeros((batch, h, w, 4), np.uint8)
        img[:, 4 + i % 8: 16 + i % 8, 6:26] = (i % 5) + 1
        xy = np.full((batch, 8, 2), float(i), np.float32)
        frames = encode_message(
            {"btid": 0, "_prebatched": True, "image": img, "xy": xy},
            compress_rle=True, rle_cap=256, compress_min_bytes=512,
            state=state,
        )
        out.append(decode_message(frames, defer_rle=deferred))
    return out


def test_pipeline_decodes_deferred_rle_on_device():
    """A deferred 'ndr' stream through the NON-fused pipeline: the
    standalone device decode expands the run buffers in its jit and the
    consumer sees exact full frames (no host inflate anywhere)."""
    msgs = _ndr_messages(5)
    expect = _ndr_messages(5, deferred=False)
    assert "image__ndr" in msgs[0]
    with StreamDataPipeline(iter(msgs), batch_size=4) as pipe:
        got = list(pipe)
    assert len(got) == 5
    for g, e in zip(got, expect):
        np.testing.assert_array_equal(np.asarray(g["image"]), e["image"])
        np.testing.assert_array_equal(np.asarray(g["xy"]), e["xy"])


def test_place_in_driver_requires_emit_packed():
    with pytest.raises(ValueError, match="emit_packed"):
        StreamDataPipeline(
            iter([]), batch_size=4, place_in_driver=True
        )


def test_place_in_driver_yields_host_batches_with_plans():
    msgs = _ndr_messages(3)
    pipe = StreamDataPipeline(
        iter(msgs), batch_size=4, emit_packed=True, place_in_driver=True
    )
    with pipe:
        got = list(pipe)
    assert len(got) == 3
    for b in got:
        assert isinstance(b["_packed"], np.ndarray)  # still host-side
        assert b["_rle"] and b["_rle"][0][0] == "image"
        assert "_spec" in b and "_pal" in b
    # the feeder's public place() commits ONE grouped transfer
    placed = pipe.feeder.place(dict(got[0]))
    assert isinstance(placed["_packed"], jax.Array)
    assert placed["_rle"] == got[0]["_rle"]  # plan sidecars untouched


def test_place_plan_memoized_per_schema_fingerprint():
    """Satellite: steady-state placement resolves the field grouping
    once per batch shape — one plan entry, one grouped device_put call
    per batch, identical placement semantics."""
    feeder = DeviceFeeder()
    batches = [
        {
            "image": np.full((8, 4, 4, 4), i, np.uint8),
            "xy": np.zeros((8, 2), np.float32),
            "btid": 7,
            "_meta": [{}] * 8,
        }
        for i in range(6)
    ]
    calls = []
    real_put = jax.device_put

    def counting_put(x, *a, **k):
        calls.append(1)
        return real_put(x, *a, **k)

    import blendjax.data.pipeline as pl

    orig = pl._require_jax

    class _J:
        def __getattr__(self, name):
            if name == "device_put":
                return counting_put
            return getattr(jax, name)

    pl._require_jax = lambda: _J()
    try:
        out = [feeder._place(b) for b in batches]
    finally:
        pl._require_jax = orig
    assert len(calls) == len(batches)  # ONE grouped call per batch
    assert len(feeder._place_plans) == 1  # one fingerprint, one plan
    for i, o in enumerate(out):
        np.testing.assert_array_equal(np.asarray(o["image"]), batches[i]["image"])
        assert o["btid"] == 7 and o["_meta"] == batches[i]["_meta"]
    # a different batch shape builds a second plan, not a wrong reuse
    feeder._place({"image": np.zeros((8, 4, 4, 4), np.uint8)})
    assert len(feeder._place_plans) == 2


def test_driver_place_replicates_packed_buffer_on_mesh():
    """`_packed` (the post-plan rename of `__packed__` in driver-
    placement mode) must replicate on a mesh, never take the batch
    sharding — byte-sharding a packed buffer splits fields mid-array."""
    mesh, sharding = _data_sharding()
    feeder = DeviceFeeder(sharding=sharding)
    batch = {
        "_packed": np.zeros((3, 100), np.uint8),  # 3 % 8 != 0 on purpose
        "_spec": (("image", "|u1", (3, 4, 4, 4), 0, 192),),
        "_pal": (),
        "_rle": (),
        "_meta": [{}],
    }
    placed = feeder.place(batch)
    assert isinstance(placed["_packed"], jax.Array)
    assert len(placed["_packed"].sharding.device_set) == len(mesh.devices.flat)
    # replicated: every device holds the WHOLE buffer
    shard = next(iter(placed["_packed"].addressable_shards))
    assert shard.data.shape == (3, 100)
    assert placed["_spec"] == batch["_spec"]  # sidecars pass through
