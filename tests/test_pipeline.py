"""Device-feeding pipeline on the virtual 8-device CPU mesh: the full
ingest path (producers -> sockets -> batches -> sharded global arrays),
i.e. the blendjax replacement for DataLoader+collate+.cuda()."""

import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from blendjax.data import DeviceFeeder, StreamDataPipeline  # noqa: E402

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen", "cube_producer.py"
)


def _data_sharding():
    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    return mesh, NamedSharding(mesh, P("data"))


def test_device_feeder_shards_batch_on_mesh():
    mesh, sharding = _data_sharding()
    batches = [
        {
            "image": np.full((8, 4, 4, 4), i, np.uint8),
            "frameid": np.arange(8),
            "_meta": [{"btid": 0}] * 8,
        }
        for i in range(4)
    ]
    feeder = DeviceFeeder(sharding=sharding, prefetch=2)
    out = list(feeder(batches))
    assert len(out) == 4
    for i, b in enumerate(out):
        assert isinstance(b["image"], jax.Array)
        assert b["image"].sharding == sharding
        # batch axis split across the 8 devices: one item per device
        shard_shapes = {s.data.shape for s in b["image"].addressable_shards}
        assert shard_shapes == {(1, 4, 4, 4)}
        assert b["_meta"][0]["btid"] == 0  # metadata stays host-side
        np.testing.assert_array_equal(np.asarray(b["frameid"]), np.arange(8))


def test_stream_pipeline_end_to_end_with_producers():
    from blendjax.launcher import PythonProducerLauncher

    mesh, sharding = _data_sharding()
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA"],
        seed=1,
        instance_args=[["--shape", "32", "32"]] * 2,
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=8,
            sharding=sharding,
            timeoutms=20000,
        ) as pipe:
            it = iter(pipe)
            seen_btids = set()
            # Producers start at different times on a loaded host (a
            # fast first producer can feed MANY batches before the
            # second finishes importing), so the fan-in wait is TIME
            # bounded, not batch-count bounded.
            deadline = time.time() + 30
            i = 0
            while time.time() < deadline:
                batch = next(it)
                assert batch["image"].shape == (8, 32, 32, 4)
                assert batch["image"].sharding == sharding
                assert batch["image"].dtype == np.uint8
                seen_btids |= {m.get("btid") for m in batch["_meta"]}
                if i >= 3 and seen_btids == {0, 1}:
                    break
                i += 1
            assert pipe.queue_depth() >= 0
    assert seen_btids == {0, 1}


def test_batched_producer_end_to_end_and_tail_flush():
    """--batch mode: producer publishes (B, ...) messages; a --frames count
    that is not a multiple of --batch still delivers every frame (the tail
    partial batch is flushed at shutdown and re-batched by ingest)."""
    from blendjax.data import RemoteStream
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=1,
        instance_args=[["--shape", "32", "32", "--batch", "4", "--frames", "10"]],
    ) as launcher:
        stream = RemoteStream(
            launcher.addresses["DATA"], timeoutms=40000, max_items=3
        )
        frames = []
        for msg in stream:
            assert msg["_batched"] is True
            frames.extend(msg["frameid"].tolist())
        assert sorted(frames) == list(range(1, 11))


def test_device_feeder_multihost_assembles_global_batch():
    """multihost=True routes through jax.make_array_from_process_local_data
    (degenerate single-process case here: local data == global batch);
    the result is a global array under the requested sharding."""
    mesh, sharding = _data_sharding()
    batches = [
        {
            "image": np.arange(8 * 4 * 4 * 4, dtype=np.uint8).reshape(
                8, 4, 4, 4
            ),
            "frameid": np.arange(8),
        }
    ]
    feeder = DeviceFeeder(sharding=sharding, prefetch=1, multihost=True)
    (out,) = list(feeder(batches))
    assert out["image"].sharding.is_equivalent_to(sharding, 4)
    np.testing.assert_array_equal(np.asarray(out["image"]), batches[0]["image"])
