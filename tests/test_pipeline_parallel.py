"""Pipeline (pp) and expert (ep) parallelism — net-new vs the reference
(SURVEY.md §2.4 lists both as absent). Runs on the virtual 8-device CPU
mesh from conftest."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from blendjax.parallel import (  # noqa: E402
    create_mesh,
    pipeline_apply,
    shard_params,
    stack_stage_params,
)


def _stage_fn(params, x):
    # One shape-preserving MLP stage: x @ w + b, gelu.
    return jax.nn.gelu(x @ params["w"] + params["b"])


def _make_stages(key, n_stages, dim):
    stages = []
    for i in range(n_stages):
        k = jax.random.fold_in(key, i)
        stages.append({
            "w": jax.random.normal(k, (dim, dim), jnp.float32) / np.sqrt(dim),
            "b": jnp.zeros((dim,), jnp.float32),
        })
    return stages


def _sequential(stages, x):
    y = x
    for p in stages:
        y = _stage_fn(p, y)
    return y


def test_pipeline_matches_sequential():
    n_stages, m, mb, dim = 4, 8, 2, 16
    mesh = create_mesh({"pipe": n_stages}, devices=jax.devices()[:n_stages])
    stages = _make_stages(jax.random.key(0), n_stages, dim)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(1), (m, mb, dim), jnp.float32)

    y = pipeline_apply(_stage_fn, stacked, x, mesh, axis="pipe")
    ref = jnp.stack([_sequential(stages, x[i]) for i in range(m)])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_pipeline_grads_match_sequential():
    n_stages, m, mb, dim = 2, 4, 2, 8
    mesh = create_mesh({"pipe": n_stages}, devices=jax.devices()[:n_stages])
    stages = _make_stages(jax.random.key(2), n_stages, dim)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(3), (m, mb, dim), jnp.float32)

    def loss_pipe(p):
        return jnp.mean(pipeline_apply(_stage_fn, p, x, mesh) ** 2)

    def loss_seq(p):
        unstacked = [
            jax.tree_util.tree_map(lambda a: a[i], p)
            for i in range(n_stages)
        ]
        return jnp.mean(
            jnp.stack([_sequential(unstacked, x[i]) for i in range(m)]) ** 2
        )

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4,
                                   atol=2e-5)


def test_pipeline_degenerate_no_axis():
    # Mesh without a pipe axis: stages applied sequentially, same result.
    n_stages, m, mb, dim = 3, 4, 2, 8
    mesh = create_mesh({"data": 1}, devices=jax.devices()[:1])
    stages = _make_stages(jax.random.key(4), n_stages, dim)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(5), (m, mb, dim), jnp.float32)
    y = pipeline_apply(_stage_fn, stacked, x, mesh, axis="pipe")
    ref = jnp.stack([_sequential(stages, x[i]) for i in range(m)])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)


def test_pipeline_composes_with_jit_and_data_axis():
    # pipe x data mesh: batch sharded on data, stages on pipe, under jit.
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = create_mesh({"pipe": 2, "data": 2},
                       devices=jax.devices()[:4])
    stages = _make_stages(jax.random.key(6), 2, 8)
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(7), (4, 4, 8), jnp.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "data")))

    @jax.jit
    def f(p, x):
        return pipeline_apply(_stage_fn, p, x, mesh)

    y = f(stacked, xs)
    ref = jnp.stack([_sequential(stages, x[i]) for i in range(4)])
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), rtol=2e-5,
                               atol=2e-5)
    # Batch stays sharded on 'data' through the pipeline (no gather).
    assert "data" in str(y.sharding.spec)


def test_pipeline_rejects_stage_count_mismatch():
    mesh = create_mesh({"pipe": 2}, devices=jax.devices()[:2])
    stages = _make_stages(jax.random.key(8), 4, 8)  # 4 stages, pipe=2
    stacked = stack_stage_params(stages)
    x = jax.random.normal(jax.random.key(9), (4, 2, 8), jnp.float32)
    with pytest.raises(AssertionError, match="leading dim"):
        pipeline_apply(_stage_fn, stacked, x, mesh, axis="pipe")


# ---------------------------------------------------------------------------
# Expert parallelism (MoE)


def test_moe_routes_all_tokens_with_ample_capacity():
    from blendjax.models import MoEMLP

    model = MoEMLP(num_experts=4, capacity_factor=4.0, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(8), (2, 16, 32), jnp.float32)
    params = model.init(jax.random.key(9), x)["params"]
    y, state = model.apply({"params": params}, x,
                           mutable=["intermediates"])
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    aux = state["intermediates"]["aux_loss"][0]
    # Balanced-ish routing keeps the Switch aux loss near 1.
    assert 0.5 < float(aux) < 4.0


def test_moe_expert_sharded_step_runs():
    # data x expert mesh; expert_* params sharded on the expert axis.
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blendjax.models import MoEMLP
    from blendjax.parallel import param_sharding_rules

    mesh = create_mesh({"data": 2, "expert": 4},
                       devices=jax.devices()[:8])
    model = MoEMLP(num_experts=4, dtype=jnp.float32)
    x = np.random.default_rng(0).normal(size=(8, 16, 32)).astype(np.float32)
    params = model.init(jax.random.key(10), x)["params"]
    params = shard_params(mesh, params)
    # The stacked expert weights must actually land on the expert axis.
    wi = params["expert_wi"]
    assert "expert" in str(wi.sharding.spec)

    xs = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def loss(p, x):
        return jnp.mean(model.apply({"params": p}, x) ** 2)

    l, g = jax.value_and_grad(loss)(params, xs)
    assert np.isfinite(float(l))
    assert all(
        np.isfinite(np.asarray(a)).all()
        for a in jax.tree_util.tree_leaves(g)
    )


def test_moe_aux_loss_reaches_gradients():
    from blendjax.models import MoEMLP, apply_with_aux

    model = MoEMLP(num_experts=4, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(12), (2, 16, 32), jnp.float32)
    params = model.init(jax.random.key(13), x)["params"]

    def loss(p):
        out, aux = apply_with_aux(model, {"params": p}, x)
        return jnp.mean(out**2) + aux

    def loss_no_aux(p):
        return jnp.mean(model.apply({"params": p}, x) ** 2)

    _, aux = apply_with_aux(model, {"params": params}, x)
    assert float(aux) > 0.0
    g = jax.grad(loss)(params)["router"]["kernel"]
    g0 = jax.grad(loss_no_aux)(params)["router"]["kernel"]
    # The aux term changes the router's gradient (balancing pressure).
    assert not np.allclose(np.asarray(g), np.asarray(g0))


def test_streamformer_with_moe_blocks():
    from blendjax.models import StreamFormer

    model = StreamFormer(patch=8, dim=32, depth=2, num_heads=4,
                         num_outputs=16, num_experts=2,
                         dtype=jnp.float32)
    images = np.zeros((2, 32, 32, 4), np.uint8)
    params = model.init(jax.random.key(11), images)["params"]
    out = model.apply({"params": params}, images)
    assert out.shape == (2, 16)
    # MoE blocks really exist: expert-stacked weights present in block 0.
    flat = jax.tree_util.tree_leaves_with_path(params)
    assert any("expert_wi" in jax.tree_util.keystr(p) for p, _ in flat)
