"""Uniform precision policies (PR 9): ``blendjax.train.precision``.

- resolution rules (name / instance / None-default) and the model
  constructors' dtype flowing from the policy instead of per-file
  constants,
- ``bf16-compute`` (the default) trains bit-identically to the
  pre-policy behavior (f32 grads, f32 params),
- ``bf16-grads`` carries bf16 cotangents through the backward pass
  (the bytes that cross the mesh) while the optimizer still sees f32
  grads on f32 master params, and accumulation stays f32,
- the policy threads through every step builder (per-batch, chunked,
  accum, echo-fused) without changing the default path's math.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

from blendjax.models import CubeRegressor  # noqa: E402
from blendjax.train import (  # noqa: E402
    make_supervised_step,
    make_train_state,
)
from blendjax.train.precision import (  # noqa: E402
    BF16_COMPUTE,
    BF16_GRADS,
    DEFAULT_POLICY,
    F32,
    PrecisionPolicy,
    cast_floating,
    default_compute_dtype,
    policy_value_and_grad,
    resolve_policy,
)

B, H, W = 4, 8, 8


def _batch(seed: int = 0) -> dict:
    rng = np.random.default_rng(seed)
    return {
        "image": rng.integers(0, 255, (B, H, W, 4), np.uint8),
        "xy": (rng.random((B, 8, 2)) * H).astype(np.float32),
    }


def _state(model=None):
    return make_train_state(
        model or CubeRegressor(features=(8,)),
        np.zeros((B, H, W, 4), np.uint8),
        optimizer=optax.sgd(0.01), rng=jax.random.key(0),
    )


# -- resolution ----------------------------------------------------------------


def test_policy_resolution_rules():
    assert resolve_policy(None) is DEFAULT_POLICY
    assert resolve_policy("f32") is F32
    assert resolve_policy("bf16-grads") is BF16_GRADS
    assert resolve_policy(BF16_COMPUTE) is BF16_COMPUTE
    custom = PrecisionPolicy("mine", compute_dtype=jnp.float16)
    assert resolve_policy(custom) is custom
    with pytest.raises(ValueError, match="unknown precision policy"):
        resolve_policy("bf17")


def test_default_policy_is_bf16_compute_with_f32_everything_else():
    assert DEFAULT_POLICY is BF16_COMPUTE
    assert DEFAULT_POLICY.compute_dtype == jnp.bfloat16
    assert DEFAULT_POLICY.param_dtype == jnp.float32
    assert DEFAULT_POLICY.grad_reduce_dtype is None
    assert DEFAULT_POLICY.accum_dtype == jnp.float32


def test_models_resolve_dtype_from_policy():
    """Model files carry no dtype constants anymore: ``dtype=None``
    resolves through the policy; an explicit dtype (or
    ``policy.module_kwargs()``) still wins."""
    assert default_compute_dtype(None) == jnp.bfloat16
    assert default_compute_dtype(jnp.float32) == jnp.float32
    m = CubeRegressor(features=(8,))
    assert m.dtype is None
    v = m.init(jax.random.key(0), np.zeros((1, H, W, 4), np.uint8))
    out = m.apply(v, np.zeros((1, H, W, 4), np.uint8))
    assert out.dtype == jnp.float32  # head stays f32 by design
    mf = CubeRegressor(features=(8,), **F32.module_kwargs())
    assert mf.dtype == jnp.float32


def test_cast_floating_leaves_integers_alone():
    tree = {"w": jnp.ones((2,), jnp.float32),
            "img": jnp.zeros((2,), jnp.uint8),
            "n": jnp.zeros((), jnp.int32)}
    low = cast_floating(tree, jnp.bfloat16)
    assert low["w"].dtype == jnp.bfloat16
    assert low["img"].dtype == jnp.uint8
    assert low["n"].dtype == jnp.int32


# -- grad path -----------------------------------------------------------------


def test_bf16_grads_cotangents_are_bf16_then_cast_back():
    """The policy's point: the backward pass (and therefore the
    cross-chip gradient all-reduce of a data-sharded step) runs on
    bf16 cotangents; the optimizer sees f32 grads on f32 masters."""
    params = {"w": jnp.ones((4, 4), jnp.float32)}
    seen = {}

    def loss(p):
        # record the dtype differentiation actually runs in
        seen["dtype"] = p["w"].dtype
        return (p["w"].astype(jnp.float32) ** 2).sum()

    val, grads = policy_value_and_grad(loss, params, BF16_GRADS)
    assert seen["dtype"] == jnp.bfloat16  # differentiated w.r.t. bf16
    assert grads["w"].dtype == jnp.float32  # cast back for the optimizer
    # and the default policy is a plain value_and_grad
    val2, grads2 = policy_value_and_grad(loss, params, BF16_COMPUTE)
    assert seen["dtype"] == jnp.float32
    assert grads2["w"].dtype == jnp.float32
    np.testing.assert_allclose(float(val), float(val2), rtol=1e-2)


def test_default_policy_step_is_bit_identical_to_unspecified():
    """precision=None and precision='bf16-compute' are the SAME step:
    the policy refactor must not move the default path's numerics."""
    batch = _batch()
    s1, m1 = make_supervised_step(donate=False)(_state(), batch)
    s2, m2 = make_supervised_step(donate=False, precision="bf16-compute")(
        _state(), batch
    )
    assert float(np.asarray(m1["loss"])) == float(np.asarray(m2["loss"]))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        s1.params, s2.params,
    )


@pytest.mark.parametrize("accum", [1, 2])
def test_bf16_grads_step_trains(accum):
    """bf16-grads changes grad bytes, not trainability: finite loss,
    f32 params actually move, microbatch accumulation included (f32
    accumulation of bf16-reduced grads)."""
    step = make_supervised_step(
        donate=False, precision="bf16-grads", accum_steps=accum
    )
    s0 = _state()
    before = jax.tree.map(np.asarray, s0.params)
    s1, m = step(s0, _batch())
    assert np.isfinite(float(np.asarray(m["loss"])))
    moved = jax.tree_util.tree_leaves(jax.tree.map(
        lambda a, b: bool((np.asarray(a) != np.asarray(b)).any()),
        before, s1.params,
    ))
    assert any(moved)
    leaf = jax.tree_util.tree_leaves(s1.params)[0]
    assert leaf.dtype == jnp.float32  # masters stay f32


def test_chunked_step_threads_policy():
    from blendjax.train import make_chunked_supervised_step

    batch = _batch()
    sb = {k: np.stack([v, v]) for k, v in batch.items()}
    step = make_chunked_supervised_step(
        donate=False, precision="bf16-grads"
    )
    s1, m = step(_state(), sb)
    assert m["loss"].shape == (2,)
    assert np.isfinite(np.asarray(m["loss"])).all()


def test_echo_fused_step_threads_policy():
    from blendjax.data.echo import SampleReservoir
    from blendjax.train import make_echo_fused_step

    res = SampleReservoir(capacity=8, augment=None)
    res.insert(_batch())
    step = make_echo_fused_step(
        reservoir_draw=res.draw, donate=False, precision="bf16-grads"
    )
    s1, m = step(_state(), res.draw_token(np.arange(B)))
    assert np.isfinite(float(np.asarray(m["loss"])))


def test_f32_policy_with_f32_model_is_full_precision():
    model = CubeRegressor(features=(8,), **F32.module_kwargs())
    step = make_supervised_step(donate=False, precision="f32")
    s1, m = step(_state(model), _batch())
    assert np.isfinite(float(np.asarray(m["loss"])))
