"""Kill -9 mid-run -> resume -> f32 loss trajectory identical to an
uninterrupted run (ISSUE 12 acceptance): pinned on single-chip, on the
8-device CPU mesh, and through an elastic 8 -> 4 resharded resume —
the PR 8 mesh-equality trick applied to TIME instead of mesh size.

Mechanism: ``tests/ckpt_worker.py`` trains a deterministic seeded
stream through the real mesh pipeline with async checkpointing. The
kill leg runs paced so the parent can observe a COMMITTED snapshot
(manifest present — the atomic-rename contract) and SIGKILL the
process mid-run; the resume leg restores the latest snapshot, fast-
forwards the stream, and continues. SIGKILL gives no cleanup window,
so everything the resumed run has IS what the async writer committed.
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import time

import numpy as np

WORKER = os.path.join(os.path.dirname(__file__), "ckpt_worker.py")

# same-mesh resume replays identical float ops -> exact equality;
# cross-mesh resume inherits the collective-reduction-reorder bar the
# 1-vs-8 equality tests pin (tests/test_mesh_driver.py)
F32_EXACT_ATOL = 5e-6


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run(args, timeout=300):
    proc = subprocess.run(
        [sys.executable, WORKER, *args],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=timeout,
    )
    assert proc.returncode == 0, proc.stdout
    return proc.stdout


def _losses(path):
    with open(path) as f:
        return json.load(f)["losses"]


def _wait_committed(directory, timeout=180):
    """Poll for at least one COMMITTED snapshot — through the
    subsystem's own read-only commit predicate, so a format rename
    can't silently turn this poll into a timeout."""
    from blendjax.checkpoint import committed_steps

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if committed_steps(directory):
            return True
        time.sleep(0.05)
    return False


def _kill9_mid_run(directory, mesh, steps):
    """Start a paced worker, SIGKILL it after the first commit; assert
    it really died mid-run."""
    proc = subprocess.Popen(
        [sys.executable, WORKER, directory, "--steps", str(steps),
         "--mesh", str(mesh), "--ckpt-every", "2", "--pace", "0.5"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        committed = _wait_committed(directory)
        assert committed, (
            "no committed snapshot before timeout:\n"
            + proc.communicate(timeout=10)[0]
        )
    finally:
        if proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)
    out, _ = proc.communicate(timeout=60)
    assert proc.returncode == -signal.SIGKILL, (
        f"worker was not killed mid-run (rc={proc.returncode}):\n{out}"
    )


def test_kill9_resume_single_chip_trajectory_identical(tmp_path):
    steps = 10
    ref_out = tmp_path / "ref.json"
    _run([str(tmp_path / "ref"), "--steps", str(steps), "--mesh", "1",
          "--ckpt-every", "2", "--out", str(ref_out)])
    kill_dir = str(tmp_path / "kill")
    _kill9_mid_run(kill_dir, mesh=1, steps=steps)
    res_out = tmp_path / "res.json"
    out = _run([kill_dir, "--steps", str(steps), "--mesh", "1",
                "--resume", "--out", str(res_out)])
    assert "ckpt_worker done" in out
    ref, res = _losses(ref_out), _losses(res_out)
    assert len(ref) == len(res) == steps
    # identical, not close: same program, same stream, same backend —
    # the restart is invisible to the math
    assert res == ref


def test_kill9_resume_8dev_mesh_and_elastic_8_to_4(tmp_path):
    steps = 8
    ref_out = tmp_path / "ref8.json"
    _run([str(tmp_path / "ref8"), "--steps", str(steps), "--mesh", "8",
          "--ckpt-every", "2", "--out", str(ref_out)])
    kill_dir = str(tmp_path / "kill8")
    _kill9_mid_run(kill_dir, mesh=8, steps=steps)
    # each resume leg starts from the SAME kill-time snapshot: copy the
    # directory so the first resume's own cadence saves can't feed the
    # second
    elastic_dir = str(tmp_path / "kill8-elastic")
    shutil.copytree(kill_dir, elastic_dir)

    res8_out = tmp_path / "res8.json"
    _run([kill_dir, "--steps", str(steps), "--mesh", "8", "--resume",
          "--out", str(res8_out)])
    ref, res8 = _losses(ref_out), _losses(res8_out)
    assert res8 == ref  # same mesh: bitwise

    # elastic: the preempted 8-chip job continues on 4 chips — the
    # snapshot's global arrays re-place under the 4-way shardings
    # (state_shardings on the new mesh) and the trajectory matches to
    # the established f32 collective-reorder bar
    res4_out = tmp_path / "res4.json"
    out = _run([elastic_dir, "--steps", str(steps), "--mesh", "4",
                "--resume", "--out", str(res4_out)])
    assert "ckpt_worker done" in out
    res4 = _losses(res4_out)
    assert len(res4) == steps
    np.testing.assert_allclose(res4, ref, rtol=0, atol=F32_EXACT_ATOL)
