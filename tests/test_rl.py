"""blendjax.rl: trajectory replay, actor pool, fused learner steps,
the env-bound/learner-bound doctor, and checkpoint/resume — all
hermetic (a fake vector env; no sockets, no producers)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from blendjax.models import QNetwork  # noqa: E402
from blendjax.rl import (  # noqa: E402
    ActorPool,
    HostQPolicy,
    RLTrainDriver,
    TrajectoryReservoir,
    diagnose_rl,
    make_dqn_step,
    make_pg_step,
    make_rl_train_state,
    np_mlp_forward,
)
from blendjax.utils.metrics import metrics  # noqa: E402


class FakeVecEnv:
    """Deterministic 4-dim vector env with fixed-horizon episodes and
    the BatchedRemoteEnv contract (auto-reset + final_observation)."""

    def __init__(self, n=4, horizon=12, seed=0):
        self.n = n
        self.h = horizon
        self.rng = np.random.default_rng(seed)
        self.t = np.zeros(n, int)
        self.steps = 0

    def _obs(self):
        return self.rng.normal(size=(self.n, 4)).astype(np.float32)

    def reset(self, seed=None):
        self.t[:] = 0
        return self._obs(), [{} for _ in range(self.n)]

    def step(self, actions):
        self.steps += 1
        self.t += 1
        done = self.t >= self.h
        obs = self._obs()
        infos = [{} for _ in range(self.n)]
        for i in np.flatnonzero(done):
            # terminal obs deliberately distinctive so tests can assert
            # it reached next_obs instead of the fresh episode's start
            infos[i]["final_observation"] = np.full(4, 9.0, np.float32)
            self.t[i] = 0
        return obs, np.ones(self.n, np.float32), done, infos


def _insert_batch(res, n=8, seed=0, with_ret=False):
    rng = np.random.default_rng(seed)
    batch = {
        "obs": rng.normal(size=(n, 4)).astype(np.float32),
        "action": rng.integers(0, 3, size=n).astype(np.int32),
        "reward": np.ones(n, np.float32),
        "done": np.zeros(n, bool),
        "next_obs": rng.normal(size=(n, 4)).astype(np.float32),
    }
    if with_ret:
        batch["ret"] = rng.normal(size=n).astype(np.float32)
    return res.insert(batch)


# -- TrajectoryReservoir ------------------------------------------------------


def test_reservoir_insert_gather_round_trip_and_wraparound():
    res = TrajectoryReservoir(16)
    slots = _insert_batch(res, 8)
    assert list(slots) == list(range(8))
    out = res.sample(np.arange(8))
    assert set(out) == {"obs", "action", "reward", "done", "next_obs"}
    assert out["obs"].shape == (8, 4)
    # wraparound keeps size at capacity and reuses slots
    for seed in range(1, 4):
        _insert_batch(res, 8, seed=seed)
    assert res.size == 16 and res.inserts == 32


def test_reservoir_insert_buffers_stable_in_place():
    from blendjax.testing.donation import tree_pointers

    res = TrajectoryReservoir(8)
    _insert_batch(res, 8)
    before = tree_pointers(dict(res._buffers, _prio=res._priorities))
    _insert_batch(res, 8, seed=1)
    after = tree_pointers(dict(res._buffers, _prio=res._priorities))
    known = {
        k: v for k, v in before.items() if v is not None
        and after.get(k) is not None
    }
    assert known, "runtime exposed no pointers to compare"
    for k in known:
        assert before[k] == after[k], f"{k} reallocated on insert"


def test_reservoir_rejects_shape_and_structure_drift():
    res = TrajectoryReservoir(8)
    _insert_batch(res, 4)
    with pytest.raises(ValueError, match="structure"):
        res.insert({"obs": np.zeros((2, 4), np.float32)})
    with pytest.raises(ValueError, match="field"):
        _insert = {
            "obs": np.zeros((2, 5), np.float32),
            "action": np.zeros(2, np.int32),
            "reward": np.zeros(2, np.float32),
            "done": np.zeros(2, bool),
            "next_obs": np.zeros((2, 4), np.float32),
        }
        res.insert(_insert)


def test_reservoir_exact_fresh_replayed_accounting():
    res = TrajectoryReservoir(8, rng=3)
    _insert_batch(res, 8)
    idx = np.array([0, 0, 1, 2], np.int32)
    res.draw_token(idx)
    # slot 0 twice in one batch: one fresh + one replay
    assert (res.fresh, res.replayed) == (3, 1)
    res.draw_token(np.array([0, 1, 3], np.int32))
    assert (res.fresh, res.replayed) == (4, 3)
    assert res.fresh + res.replayed == 4 + 3


def test_reservoir_uniform_compose_and_insufficient_fill():
    res = TrajectoryReservoir(16, rng=0)
    assert res.compose(4) is None  # empty
    _insert_batch(res, 4)
    # with-replacement sampling: a batch may exceed the resident count
    # (the learner's min_fill gate decides how much warmup to demand)
    idx, w = res.compose(8)
    assert idx.shape == (8,) and np.all(w == 1.0)
    assert set(idx) <= {0, 1, 2, 3}


def test_reservoir_prioritized_compose_follows_priorities():
    res = TrajectoryReservoir(
        8, rng=0, prioritized=True, priority_refresh_every=1
    )
    _insert_batch(res, 8)
    # slam slot 5's priority sky-high on device, as the learner would
    res.commit_priorities(res._priorities.at[5].set(1e6))
    res._draws = res._draws_at_refresh + res.priority_refresh_every
    idx, w = res.compose(64)
    frac5 = np.mean(idx == 5)
    assert frac5 > 0.9, f"priority 1e6 slot drawn only {frac5:.0%}"
    # importance weights: the over-sampled slot gets the SMALLEST one
    if (idx != 5).any():
        assert w[idx == 5].max() <= w[idx != 5].min() + 1e-6
    else:
        assert np.allclose(w, 1.0)  # max-normalized


def test_reservoir_state_dict_round_trip_continues_sampling():
    res = TrajectoryReservoir(8, rng=7, prioritized=True)
    _insert_batch(res, 8)
    res.draw_token(*res.compose(4))
    snap = res.state_dict()
    # same-seed twin restores and continues the exact sequence
    twin = TrajectoryReservoir(8, rng=7, prioritized=True)
    twin.load_state_dict(snap)
    a = res.compose(4)
    b = twin.compose(4)
    assert np.array_equal(a[0], b[0]) and np.allclose(a[1], b[1])
    assert twin.size == res.size and twin.inserts == res.inserts
    assert (twin.fresh, twin.replayed) == (res.fresh, res.replayed)
    got = twin.sample(np.arange(8))
    want = res.sample(np.arange(8))
    for k in want:
        np.testing.assert_array_equal(
            np.asarray(got[k]), np.asarray(want[k])
        )


def test_reservoir_capacity_mismatch_refuses_restore():
    res = TrajectoryReservoir(8)
    _insert_batch(res, 4)
    snap = res.state_dict()
    with pytest.raises(ValueError, match="capacity"):
        TrajectoryReservoir(16).load_state_dict(snap)


# -- host policy / actor pool -------------------------------------------------


def test_np_mlp_forward_matches_flax_apply():
    model = QNetwork(hidden=(16, 8), n_actions=3)
    obs = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    params = model.init(jax.random.key(0), obs)["params"]
    want = np.asarray(model.apply({"params": params}, obs))
    got = np_mlp_forward(jax.device_get(params), obs)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_host_q_policy_random_until_snapshot_then_greedy():
    pol = HostQPolicy(3, eps_start=0.0, eps_end=0.0, seed=0)
    obs = np.zeros((4, 4), np.float32)
    a = pol(None, obs)
    assert a.shape == (4,) and a.dtype == np.int32
    model = QNetwork(hidden=(8,), n_actions=3)
    params = jax.device_get(
        model.init(jax.random.key(1), obs)["params"]
    )
    q = np_mlp_forward(params, obs)
    greedy = pol(params, obs)
    assert np.array_equal(greedy, np.argmax(q, axis=-1))


def test_actor_pool_feeds_reservoir_with_final_obs_bootstrap():
    res = TrajectoryReservoir(256)
    env = FakeVecEnv(n=4, horizon=3)
    pool = ActorPool(env, res, HostQPolicy(3, seed=0))
    with pool:
        import time

        deadline = time.monotonic() + 20
        while res.inserts < 48 and time.monotonic() < deadline:
            time.sleep(0.01)
    assert res.inserts >= 48
    # exact identity: every env row stepped == one inserted transition
    assert pool.env_steps == res.inserts
    assert pool.episodes >= 4
    # done rows bootstrapped from final_observation (the 9.0 stamp),
    # never from the fresh episode's first obs
    out = res.sample(np.arange(res.size))
    done = np.asarray(out["done"])
    nxt = np.asarray(out["next_obs"])
    assert done.any()
    assert np.allclose(nxt[done], 9.0)
    assert not np.allclose(nxt[~done], 9.0)


def test_actor_pool_state_dict_round_trip():
    res = TrajectoryReservoir(64)
    pool = ActorPool(
        FakeVecEnv(n=2, horizon=4), res, HostQPolicy(3, seed=2)
    )
    pool.env_steps = 40
    pool.episodes = 5
    pool.episode_returns = [(8, 4.0), (40, 4.0)]
    pool.policy.calls = 17
    snap = pool.state_dict()
    twin = ActorPool(
        FakeVecEnv(n=2, horizon=4), res, HostQPolicy(3, seed=2)
    )
    twin.load_state_dict(snap)
    assert twin.env_steps == 40 and twin.episodes == 5
    assert twin.episode_returns == [(8, 4.0), (40, 4.0)]
    assert twin.policy.calls == 17


def test_actor_pool_surfaces_thread_errors_via_check():
    class DeadEnv(FakeVecEnv):
        def step(self, actions):
            raise RuntimeError("env exploded")

    res = TrajectoryReservoir(16)
    pool = ActorPool(DeadEnv(n=2), res, HostQPolicy(3))
    with pool:
        import time

        deadline = time.monotonic() + 10
        while pool._error is None and time.monotonic() < deadline:
            time.sleep(0.01)
    with pytest.raises(RuntimeError, match="actor loop died"):
        pool.check()
    # a restart after a transient death comes up healthy: start()
    # clears the stale error instead of re-raising it forever
    healthy = ActorPool(FakeVecEnv(n=2), res, HostQPolicy(3))
    healthy._error = RuntimeError("stale")
    with healthy:
        healthy.check()


# -- fused learner steps ------------------------------------------------------


def _train_setup(prioritized=False, pg=False, capacity=64):
    res = TrajectoryReservoir(capacity, rng=0, prioritized=prioritized)
    model = QNetwork(hidden=(16,), n_actions=3)
    state = make_rl_train_state(
        model, np.zeros((1, 4), np.float32), target=not pg
    )
    if pg:
        step = make_pg_step(res, model.apply)
    else:
        step = make_dqn_step(res, model.apply)
    return res, model, state, step


def test_dqn_step_one_dispatch_updates_state_and_priorities():
    res, model, state, step = _train_setup(prioritized=True)
    _insert_batch(res, 32)
    prio_before = np.array(res._priorities)
    p0 = jax.device_get(state.params)
    token = res.draw_token(*res.compose(16))
    state, m = step(state, token)
    assert np.isfinite(float(m["loss"]))
    p1 = jax.device_get(state.params)
    changed = any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(p0), jax.tree.leaves(p1))
    )
    assert changed, "params did not update"
    # priorities rewritten in-jit at the drawn slots
    prio_after = np.array(res._priorities)
    drawn = np.unique(token["_rl_idx"])
    assert not np.allclose(prio_before[drawn], prio_after[drawn])
    untouched = np.setdiff1d(np.arange(res.capacity), drawn)
    np.testing.assert_array_equal(
        prio_before[untouched], prio_after[untouched]
    )


def test_dqn_target_polyak_moves_inside_the_same_dispatch():
    res, model, state, step = _train_setup()
    _insert_batch(res, 32)
    t0 = jax.device_get(state.target_params)
    state, _ = step(state, res.draw_token(*res.compose(16)))
    t1 = jax.device_get(state.target_params)
    moved = any(
        not np.allclose(a, b)
        for a, b in zip(jax.tree.leaves(t0), jax.tree.leaves(t1))
    )
    assert moved, "target network froze (tau ignored)"


def test_pg_step_trains_on_returns():
    res, model, state, step = _train_setup(pg=True)
    rng = np.random.default_rng(0)
    res.insert({
        "obs": rng.normal(size=(32, 4)).astype(np.float32),
        "action": rng.integers(0, 3, size=32).astype(np.int32),
        "reward": np.ones(32, np.float32),
        "done": np.zeros(32, bool),
        "next_obs": rng.normal(size=(32, 4)).astype(np.float32),
        "ret": rng.normal(size=32).astype(np.float32),
    })
    state, m = step(state, res.draw_token(*res.compose(16)))
    assert np.isfinite(float(m["loss"]))


def test_learner_driver_end_to_end_exact_accounting():
    metrics.reset()
    res = TrajectoryReservoir(128, rng=0, prioritized=True)
    env = FakeVecEnv(n=4, horizon=8)
    pool = ActorPool(env, res, HostQPolicy(3, eps_steps=64, seed=1))
    model = QNetwork(hidden=(16,), n_actions=3)
    state = make_rl_train_state(model, np.zeros((1, 4), np.float32))
    step = make_dqn_step(res, model.apply)
    driver = RLTrainDriver(
        step, state, res, actors=pool, batch_size=16, min_fill=32,
        sync_every=4, inflight=2,
    )
    with pool:
        loss = driver.run_steps(12)
    assert np.isfinite(loss)
    assert driver.steps == 12 and driver.dispatches == 12
    # the seq-style identity: every drawn row accounted exactly once
    assert res.fresh + res.replayed == 12 * 16
    # actors got >= 12/4 policy snapshots
    assert pool.policy_version >= 3
    # driver stats carry the rl sub-views
    s = driver.stats
    assert s["reservoir"]["draws"] == 12
    assert s["actor"]["env_steps"] == res.inserts


def test_learner_driver_times_out_without_actors():
    res = TrajectoryReservoir(64)
    model = QNetwork(hidden=(8,), n_actions=3)
    state = make_rl_train_state(model, np.zeros((1, 4), np.float32))
    step = make_dqn_step(res, model.apply)
    driver = RLTrainDriver(
        step, state, res, batch_size=8, sample_timeout_s=0.2,
    )
    with pytest.raises(TimeoutError, match="reservoir never reached"):
        driver.train_step()


def test_learner_driver_session_round_trip(tmp_path):
    """An RL run checkpoints through the PR 11 session store and a
    fresh process-equivalent stack resumes mid-curve."""
    from blendjax.checkpoint import SnapshotManager

    res = TrajectoryReservoir(64, rng=0, prioritized=True)
    env = FakeVecEnv(n=2, horizon=6)
    pool = ActorPool(env, res, HostQPolicy(3, seed=3))
    model = QNetwork(hidden=(8,), n_actions=3)
    state = make_rl_train_state(model, np.zeros((1, 4), np.float32))
    step = make_dqn_step(res, model.apply)
    with SnapshotManager(str(tmp_path)) as mgr:
        driver = RLTrainDriver(
            step, state, res, actors=pool, batch_size=8, min_fill=16,
            checkpoint=mgr, inflight=1,
        )
        with pool:
            driver.run_steps(5)
        # actors stopped: the snapshot captures a quiesced stack, so
        # the restored twin compares exactly against the live one
        driver.checkpoint_now(wait=True)
        steps_at_save = driver.steps

        # fresh stack (same construction), restored from the snapshot
        res2 = TrajectoryReservoir(64, rng=0, prioritized=True)
        pool2 = ActorPool(
            FakeVecEnv(n=2, horizon=6), res2, HostQPolicy(3, seed=3)
        )
        model2 = QNetwork(hidden=(8,), n_actions=3)
        state2 = make_rl_train_state(
            model2, np.zeros((1, 4), np.float32)
        )
        restored = mgr.restore(state2)
        step2 = make_dqn_step(res2, model2.apply)
        driver2 = RLTrainDriver(
            step2, restored.state, res2, actors=pool2, batch_size=8,
            min_fill=16, inflight=1,
        )
        names = driver2.restore_session(restored.session)
        assert set(names) == {"replay", "actor", "driver"}
        assert driver2.steps == steps_at_save
        assert res2.inserts == res.inserts
        assert pool2.env_steps == pool.env_steps
        # the restored ring serves draws immediately (no actors needed:
        # the transitions came back with the snapshot)
        with pool2:
            loss = driver2.run_steps(2)
        assert np.isfinite(loss)
        assert driver2.steps == steps_at_save + 2


# -- the RL doctor ------------------------------------------------------------


def _report(counters=None, spans=None):
    return {"counters": counters or {}, "spans": spans or {},
            "gauges": {}}


def test_diagnose_rl_idle_without_evidence():
    v = diagnose_rl(_report())
    assert v.kind == "rl-idle"


def test_diagnose_rl_env_bound_on_sustained_sample_waits():
    v = diagnose_rl(_report(
        {"rl.transitions": 100, "rl.fresh": 90, "rl.replayed": 110,
         "rl.draws": 20, "rl.sample_waits": 3},
        {"rl.sample_wait": {"total_ms": 1200.0}},
    ))
    assert v.kind == "env-bound"
    assert "scale UP" in v.advice


def test_diagnose_rl_single_warmup_wait_is_not_sticky():
    """Every run starts with one wait at min_fill; as healthy draws
    accumulate the signal must dilute below the wait-fraction bar —
    a bare waits>0 test would ratchet the fleet to max forever."""
    v = diagnose_rl(_report(
        {"rl.transitions": 100, "rl.fresh": 100, "rl.replayed": 400,
         "rl.draws": 500, "rl.sample_waits": 1}
    ))
    assert v.kind == "rl-balanced"


def test_diagnose_rl_learner_bound_on_insert_surplus():
    v = diagnose_rl(_report(
        {"rl.transitions": 1000, "rl.fresh": 100, "rl.replayed": 100}
    ))
    assert v.kind == "learner-bound"
    assert "scale DOWN" in v.advice


def test_diagnose_rl_balanced_when_replay_absorbs_the_gap():
    v = diagnose_rl(_report(
        {"rl.transitions": 100, "rl.fresh": 100, "rl.replayed": 500}
    ))
    assert v.kind == "rl-balanced"


def test_fleet_controller_scales_on_rl_verdicts():
    """FleetPolicy.rl() + the RL verdict vocabulary drive the existing
    controller machinery unchanged (hysteresis included)."""
    from blendjax.fleet import FleetController, FleetPolicy

    class StubLauncher:
        def __init__(self):
            self.n = 1
            self.sockets = {0: {"DATA": "tcp://127.0.0.1:1"}}

        def active_indices(self):
            return list(range(self.n))

        def active_count(self):
            return self.n

        def poll_processes(self):
            return {i: None for i in self.active_indices()}

        def add_instance(self, extra_args=None):
            i = self.n
            self.n += 1
            s = {"DATA": f"tcp://127.0.0.1:{i + 1}"}
            self.sockets[i] = s
            return i, s

        def retire_instance(self, i, drain=True):
            self.n -= 1
            return self.sockets[i]

    class StubConnector:
        def __init__(self):
            self.connected = []

        def connect(self, addr):
            self.connected.append(addr)

        def disconnect(self, addr):
            self.connected.remove(addr)

    class StubLineage:
        def register(self, btid):
            pass

        def retire(self, btid):
            pass

    policy = FleetPolicy.rl(up_after=2, down_after=2, cooldown_s=0.0,
                            max_instances=3)
    assert policy.scale_up_verdicts == ("env-bound",)
    ctrl = FleetController(
        StubLauncher(), connector=StubConnector(), policy=policy,
        lineage=StubLineage(),
    )
    t = 0.0
    assert ctrl.tick("env-bound", now=t)["action"] == "hold"
    d = ctrl.tick("env-bound", now=t + 1)
    assert d["action"] == "scale_up" and d["instances"] == 2
    # learner-bound streak scales back down
    ctrl.tick("learner-bound", now=t + 2)
    d = ctrl.tick("learner-bound", now=t + 3)
    assert d["action"] == "scale_down"
    # rl-balanced resets streaks
    ctrl.tick("rl-balanced", now=t + 4)
    assert ctrl._up_streak == 0 and ctrl._down_streak == 0


# -- BJX117 regression: every reservoir entry point holds `lock` -------------


class CountingLock:
    """Context-manager probe standing in for the reservoir RLock."""

    def __init__(self):
        self.inner = __import__("threading").RLock()
        self.entries = 0

    def __enter__(self):
        self.entries += 1
        return self.inner.__enter__()

    def __exit__(self, *exc):
        return self.inner.__exit__(*exc)


def _filled_reservoir(**kw):
    res = TrajectoryReservoir(8, **kw)
    res.insert({
        "obs": np.zeros((4, 3), np.float32),
        "reward": np.ones(4, np.float32),
    })
    return res


def test_reservoir_stats_and_fields_take_the_lock():
    """PR 11's snapshot-vs-draw race class, pinned: the observability
    reads share the insert/draw critical section (BJX117 flags any
    regression statically; this is the runtime half)."""
    res = _filled_reservoir()
    probe = CountingLock()
    res.lock = probe
    assert res.stats["inserts"] == 4
    assert probe.entries == 1
    assert len(res.fields) == 2
    assert probe.entries == 2


def test_reservoir_empty_checks_run_under_the_lock():
    """draw_token/sample raise the empty-reservoir error from INSIDE
    the critical section (the pre-lock check read `_buffers` unlocked)."""
    res = TrajectoryReservoir(4)
    probe = CountingLock()
    res.lock = probe
    with pytest.raises(RuntimeError, match="insert"):
        res.draw_token(np.zeros(2, np.int32))
    with pytest.raises(RuntimeError, match="insert"):
        res.sample(np.zeros(2, np.int32))
    assert probe.entries == 2


def test_actor_stats_and_restore_share_the_accounting_cut():
    res = _filled_reservoir()
    probe = CountingLock()
    res.lock = probe
    pool = ActorPool(FakeVecEnv(), res, HostQPolicy(2))
    before = probe.entries
    assert pool.stats["env_steps"] == 0
    assert probe.entries == before + 1
    pool.load_state_dict({"env_steps": 7, "episodes": 1,
                          "episode_returns": [[7, 1.5]]})
    assert probe.entries == before + 2
    assert pool.stats["env_steps"] == 7
