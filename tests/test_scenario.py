"""blendjax.scenario: the closed-loop domain-randomization service.

Covers the tentpole contracts (docs/scenarios.md): pickle-free space
serialization, the version/ack duplex protocol over a real PairChannel,
exact per-scenario accounting with stale-version attribution, the
echo-path sidecar (echoed rows attributed to their TRUE scenario),
curriculum adaptation on synthetic fixtures, fleet-controller
membership integration with a mid-run scale-up, and an end-to-end
synthetic-fleet run with exact per-scenario histograms.
"""

import time

import numpy as np
import pytest

from blendjax.scenario import (
    SCENARIO_KEY,
    SCENARIO_ROWS_KEY,
    Gaussian,
    ScenarioAccounting,
    ScenarioCurriculum,
    ScenarioService,
    ScenarioSpace,
    Uniform,
    batch_row_scenarios,
)
from blendjax.scenario.space import Choice, Mixture

# ---------------------------------------------------------------------------
# space: grammar, sampling, wire form
# ---------------------------------------------------------------------------


def test_space_grammar_distributions_and_weights():
    sp = ScenarioSpace.parse(
        "easy:half_extent=u(0.5,0.8) / "
        "hard*3:xy_jitter=g(6,0.5),style=c(a|b|c),size=m(u(0,1)@0.7|g(2,0.1)@0.3),k=42"
    )
    assert sp.names == ("easy", "hard")
    w = sp.weights()
    assert abs(w["hard"] - 0.75) < 1e-9 and abs(sum(w.values()) - 1) < 1e-9
    hard = sp.scenarios["hard"]
    assert isinstance(hard.params["xy_jitter"], Gaussian)
    assert isinstance(hard.params["style"], Choice)
    assert isinstance(hard.params["size"], Mixture)
    assert hard.params["k"].sample(np.random.default_rng(0)) == 42
    assert isinstance(sp.scenarios["easy"].params["half_extent"], Uniform)


def test_space_sampling_bounds_and_theta_order():
    sp = ScenarioSpace.parse("s:a=u(1,2),b=g(10,0.1),c=g(-5,0.1)")
    rng = np.random.default_rng(0)
    for _ in range(32):
        name, params, theta = sp.sample(rng)
        assert name == "s"
        assert 1 <= params["a"] <= 2
        # theta lists GAUSSIAN param draws in declaration order (b, c)
        assert theta == [params["b"], params["c"]]


def test_space_wire_roundtrip_is_pickle_free():
    from blendjax.transport.wire import decode_message, encode_message

    sp = ScenarioSpace.parse(
        "easy*2:half_extent=u(0.8,1.2) / "
        "hard:xy_jitter=g(6,0.5),style=c(a|b@0.25|c)", version=7,
    )
    frames = encode_message(
        {"scenario_space": sp.to_wire(), "scenario_version": sp.version}
    )
    # allow_pickle=False: a pkl entry anywhere in the payload would raise
    msg = decode_message([bytes(f) for f in frames], allow_pickle=False)
    sp2 = ScenarioSpace.from_wire(msg["scenario_space"])
    assert sp2.version == 7
    assert sp2.names == sp.names
    assert sp2.weights() == sp.weights()
    hard = sp2.scenarios["hard"]
    assert isinstance(hard.params["xy_jitter"], Gaussian)
    assert hard.params["xy_jitter"].mu == 6.0
    assert hard.params["style"].values == ["a", "b", "c"]


def test_space_grammar_slash_inside_categorical_values():
    sp = ScenarioSpace.parse(
        "a:tex=c(wood/oak|stone/slate) / b:x=u(0,1)"
    )
    assert sp.names == ("a", "b")
    assert sp.scenarios["a"].params["tex"].values == [
        "wood/oak", "stone/slate"
    ]


def test_space_grammar_partial_weights_are_honored():
    # mixed '@w' specs: unweighted entries default to 1.0 — never a
    # silent fall-back to uniform
    sp = ScenarioSpace.parse("s:style=c(a@0.9|b),mix=m(u(0,1)@3|g(5,1))")
    c = sp.scenarios["s"].params["style"]
    assert c.probs is not None and abs(c.probs[0] - 0.9 / 1.9) < 1e-9
    m = sp.scenarios["s"].params["mix"]
    assert abs(m.weights[0] - 0.75) < 1e-9


def test_space_grammar_errors():
    with pytest.raises(ValueError):
        ScenarioSpace.parse("")
    with pytest.raises(ValueError):
        ScenarioSpace.parse("noparams-and-no-colon")
    with pytest.raises(ValueError):
        ScenarioSpace.parse("s:a=zzz(1,2)")
    with pytest.raises(ValueError):
        ScenarioSpace([])


# ---------------------------------------------------------------------------
# version/ack protocol over a real PairChannel
# ---------------------------------------------------------------------------


def test_service_publish_ack_over_real_pair_channel():
    from blendjax.producer import DuplexChannel
    from blendjax.producer.scenario import ScenarioApplicator

    applied = []
    chan = DuplexChannel("tcp://127.0.0.1:0", btid=0)
    app = ScenarioApplicator(chan, apply=applied.append, rng=0)
    sp = ScenarioSpace.parse("s:a=u(0,1)")
    svc = ScenarioService(sp)
    try:
        svc.attach(0, chan.addr)
        assert app.wait_for_space(timeout_s=10)
        assert app.version == 1
        assert svc.wait_acked(version=1, timeout=10), svc.state()
        # re-publish a bumped space: producer adopts the new version
        sp.bump()
        svc.publish(sp)
        deadline = time.monotonic() + 10
        while app.version < 2 and time.monotonic() < deadline:
            app.poll()
            time.sleep(0.01)
        assert app.version == 2
        assert svc.wait_acked(version=2, timeout=10), svc.state()
        draw = app.sample()
        assert draw.scenario == "s" and 0 <= draw.params["a"] <= 1
        assert applied and applied[-1] == draw.params
        stamp = app.next_scenario()[SCENARIO_KEY]
        assert stamp["ver"] == 2 and stamp["id"] == "s"
    finally:
        svc.stop()
        chan.close()


def test_service_detach_closes_member():
    from blendjax.producer import DuplexChannel

    from blendjax.producer.scenario import ScenarioApplicator

    chan = DuplexChannel("tcp://127.0.0.1:0", btid=0)
    app = ScenarioApplicator(chan)
    svc = ScenarioService(ScenarioSpace.parse("s:a=1"))
    try:
        svc.attach(7, chan.addr)
        assert app.wait_for_space(timeout_s=10)
        assert svc.wait_acked(timeout=10), svc.state()
        assert 7 in svc.members()
        svc.detach(7)
        assert 7 not in svc.members()
    finally:
        svc.stop()
        chan.close()


def test_service_survives_dead_member_and_malformed_acks():
    """One silently-dead member (connected endpoint, nobody there) and
    one hostile member (junk acks) must cost log lines, never the
    fleet's distribution thread: a healthy member still receives every
    republish and its acks still land."""
    from blendjax.producer import DuplexChannel
    from blendjax.producer.scenario import ScenarioApplicator

    chan = DuplexChannel("tcp://127.0.0.1:0", btid=0)
    app = ScenarioApplicator(chan)
    sp = ScenarioSpace.parse("s:a=u(0,1)")
    svc = ScenarioService(sp)
    try:
        # dead member: nothing listens on this endpoint, and PAIR send
        # would BLOCK forever without the service's send timeout
        svc.attach(99, "tcp://127.0.0.1:9")
        svc.attach(0, chan.addr)
        assert app.wait_for_space(timeout_s=10)
        # hostile member: malformed acks must not kill the thread
        chan.send(scenario_ack="junk")
        chan.send(scenario_ack=None)
        for _ in range(3):  # several republishes through the dead member
            sp.bump()
            svc.publish(sp)
        deadline = time.monotonic() + 15
        while app.version < sp.version and time.monotonic() < deadline:
            app.poll()
            time.sleep(0.01)
        assert app.version == sp.version
        assert svc.wait_acked(version=sp.version, btids=[0], timeout=10), (
            svc.state()
        )
    finally:
        svc.stop()
        chan.close()


# ---------------------------------------------------------------------------
# accounting: exact counts, versions, losses
# ---------------------------------------------------------------------------


def _stamped_batch(sid, ver, n=4, theta=None):
    stamp = {"id": sid, "ver": ver}
    if theta is not None:
        stamp["theta"] = theta
    return {
        "image": np.zeros((n, 4, 4, 3), np.uint8),
        "_meta": [{"btid": 0, SCENARIO_KEY: dict(stamp)}] * n,
    }


def test_accounting_exact_counts_and_stale_version_attribution():
    led = ScenarioAccounting()
    led.declare(ScenarioSpace.parse("a:x=1 / b:x=2", version=2))
    led.account_batch(_stamped_batch("a", 1, n=4), loss=0.5)
    led.account_batch(_stamped_batch("b", 2, n=4), loss=1.5)
    # stale-version frames (produced before the v2 publish) land under
    # the version stamped on them, not the current one
    led.account_batch(_stamped_batch("a", 1, n=2), loss=0.25)
    rep = led.report()
    a, b = rep["scenarios"]["a"], rep["scenarios"]["b"]
    assert a["rows"] == 6 and a["fresh"] == 6 and a["echoed"] == 0
    assert b["rows"] == 4
    assert a["versions"] == {1: 6}
    assert b["versions"] == {2: 4}
    # loss histograms: one observation per scored row (exact counts)
    assert a["loss"]["count"] == 6 and b["loss"]["count"] == 4
    assert a["declared"] and b["declared"]
    assert rep["space_version"] == 2


def test_accounting_batch_level_stamp_and_lead_inference():
    led = ScenarioAccounting()
    batch = {
        "image": np.zeros((3, 4, 4, 3), np.uint8),
        SCENARIO_KEY: {"id": "solo", "ver": 1},
    }
    assert led.account_batch(batch, loss=1.0) == 3
    assert led.totals() == {"solo": (3, 0)}


def test_accounting_unstamped_batches_are_a_noop():
    led = ScenarioAccounting()
    batch = {"image": np.zeros((3, 4, 4, 3), np.uint8)}
    assert led.account_batch(batch, loss=1.0) == 0
    assert led.totals() == {}


def test_accounting_overflow_folds_into_one_bucket():
    from blendjax.utils.metrics import metrics

    led = ScenarioAccounting(max_scenarios=2)
    led.account_batch(_stamped_batch("a", 1, n=1))
    led.account_batch(_stamped_batch("b", 1, n=1))
    before = metrics.counter_value("scenario.overflow_rows")
    for i in range(5):
        # loss given too: the overflow METRIC must count each row
        # once, not once per (observe_rows, observe_loss) lookup
        led.account_batch(_stamped_batch(f"junk{i}", 1, n=1), loss=1.0)
    totals = led.totals()
    assert set(totals) == {"a", "b", "__overflow__"}
    assert totals["__overflow__"] == (5, 0)
    assert metrics.counter_value("scenario.overflow_rows") - before == 5


def test_schema_keeps_scenario_meta_even_when_first_item_unstamped():
    """A mixed fleet's (or a space-timeout producer's) FIRST decoded
    item may be unstamped; the frozen schema must still carry later
    stamps into ``_meta`` or accounting reads zero forever."""
    from blendjax.data.batcher import BatchAssembler
    from blendjax.data.schema import StreamSchema

    first = {"image": np.zeros((4, 4, 3), np.uint8), "btid": 0}
    schema = StreamSchema.infer(first)
    assert SCENARIO_KEY in schema.meta_keys
    asm = BatchAssembler(schema, batch_size=2)
    assert asm.add(first) is None
    stamped = dict(first)
    stamped[SCENARIO_KEY] = {"id": "late", "ver": 2}
    batch = asm.add(stamped)
    rows = batch_row_scenarios(batch, 2)
    assert rows == [None, {"id": "late", "ver": 2}]


def test_account_batch_chunked_superbatch_meta():
    """Chunked (K, B, ...) batches carry _meta as K rest dicts each
    nesting a per-item _meta list (pipeline.py's chunk plans):
    accounting must flatten them, not silently read zero."""
    led = ScenarioAccounting()
    rests = [
        {"btid": 0, "_meta": [
            {"btid": 0, SCENARIO_KEY: {"id": "a", "ver": 1}},
            {"btid": 0, SCENARIO_KEY: {"id": "b", "ver": 1}},
        ]}
        for _ in range(3)
    ]
    batch = {"image": np.zeros((3, 2, 4, 4, 3), np.uint8), "_meta": rests}
    assert led.account_batch(batch, loss=0.5) == 6
    assert led.totals() == {"a": (3, 0), "b": (3, 0)}


def test_batch_row_scenarios_precedence():
    rows = [{"id": "r", "ver": 3}] * 2
    batch = {
        SCENARIO_ROWS_KEY: rows,
        "_meta": [{SCENARIO_KEY: {"id": "m", "ver": 1}}] * 2,
        SCENARIO_KEY: {"id": "b", "ver": 1},
    }
    assert batch_row_scenarios(batch, 2) == rows
    del batch[SCENARIO_ROWS_KEY]
    assert [r["id"] for r in batch_row_scenarios(batch, 2)] == ["m", "m"]
    del batch["_meta"]
    assert [r["id"] for r in batch_row_scenarios(batch, 2)] == ["b", "b"]


# ---------------------------------------------------------------------------
# echo path: per-row attribution stays exact
# ---------------------------------------------------------------------------


def test_echo_rows_attributed_to_true_scenario_exactly(monkeypatch):
    import blendjax.data.echo as echo_mod
    from blendjax.data.echo import EchoingPipeline

    led = ScenarioAccounting()
    monkeypatch.setattr(echo_mod, "scenario_accounting", led)

    def batches():
        # scenario alternates per INSERTED batch: echoed draws mix
        # slots across batches, so per-row attribution is the only
        # correct accounting (a batch-level stamp would lie)
        for i in range(8):
            yield _stamped_batch("even" if i % 2 == 0 else "odd", 1, n=4)

    pipe = EchoingPipeline(
        batches(), capacity=32, max_echo_factor=4, batch_size=4,
        augment=None,
    )
    steps = 0
    with pipe:
        for b in pipe:
            rows = b[SCENARIO_ROWS_KEY]
            assert len(rows) == 4 and all(
                r["id"] in ("even", "odd") for r in rows
            )
            steps += 1
    totals = led.totals()
    assert set(totals) == {"even", "odd"}
    # the exactness identity, per scenario and in total:
    # fresh + echoed == steps * batch, and fresh == first uses
    assert sum(f + e for f, e in totals.values()) == steps * 4
    assert sum(f for f, _ in totals.values()) == pipe.fresh
    assert sum(e for _, e in totals.values()) == pipe.echoed
    # each scenario inserted 16 rows; fresh can never exceed that
    assert totals["even"][0] <= 16 and totals["odd"][0] <= 16
    assert pipe.fresh + pipe.echoed == steps * 4


def test_echo_unstamped_batches_clear_slot_sidecar(monkeypatch):
    import blendjax.data.echo as echo_mod
    from blendjax.data.echo import EchoingPipeline

    led = ScenarioAccounting()
    monkeypatch.setattr(echo_mod, "scenario_accounting", led)

    def batches():
        yield _stamped_batch("a", 1, n=4)
        yield {"image": np.ones((4, 4, 4, 3), np.uint8)}  # unstamped

    pipe = EchoingPipeline(
        batches(), capacity=4, max_echo_factor=2, batch_size=4,
        augment=None,
    )
    drawn = 0
    with pipe:
        for b in pipe:
            drawn += 4
    # capacity 4: the unstamped batch overwrote every 'a' slot; rows
    # drawn after the overwrite must NOT still read as scenario 'a'
    f, e = led.totals().get("a", (0, 0))
    assert f + e <= 8  # at most the stamped batch's own echo budget


# ---------------------------------------------------------------------------
# curriculum: weights toward high loss, REINFORCE on theta
# ---------------------------------------------------------------------------


def test_curriculum_moves_weight_toward_high_loss_scenario():
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("calm:x=1 / storm:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, adapt_params=False, min_rows=4,
    )
    for _ in range(4):
        led.account_batch(_stamped_batch("calm", 1, n=4), loss=0.1)
        led.account_batch(_stamped_batch("storm", 1, n=4), loss=1.0)
    report = cur.update()
    w = sp.weights()
    assert report is not None and report["version"] == 2
    assert w["storm"] > 0.5 > w["calm"]
    # exploration floor: the easy scenario never starves
    assert w["calm"] >= cur.weight_floor


def test_curriculum_frozen_mode_never_mutates():
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("a:x=1 / b:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, adapt_params=False, frozen=True,
    )
    led.account_batch(_stamped_batch("a", 1, n=8), loss=0.1)
    led.account_batch(_stamped_batch("b", 1, n=8), loss=9.0)
    assert cur.step(1) is None
    assert sp.version == 1 and sp.weights()["a"] == 0.5


def test_curriculum_reinforce_moves_gaussian_mu():
    from blendjax.scenario import Scenario

    led = ScenarioAccounting()
    # one scenario, one gaussian param starting at 0
    sp = ScenarioSpace([Scenario("s", {"jit": Gaussian(0.0, 1.0)})])
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, min_rows=2, param_lr=0.2,
        weight_lr=0.0,
    )
    rng = np.random.default_rng(0)
    # loss = (theta - 2)^2: REINFORCE should pull mu toward 2
    for _ in range(3):
        for _ in range(16):
            theta = float(rng.normal(0.0, 1.0) + sp.scenarios["s"].params["jit"].mu)
            led.observe_rows([{"id": "s", "ver": sp.version}])
            led.observe_loss(
                [{"id": "s", "ver": sp.version, "theta": [theta]}],
                (theta - 2.0) ** 2,
            )
        cur.update()
    assert sp.scenarios["s"].params["jit"].mu > 0.15
    assert sp.version > 1


def test_curriculum_min_rows_holds_update():
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("a:x=1 / b:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, min_rows=100, adapt_params=False,
    )
    led.account_batch(_stamped_batch("a", 1, n=4), loss=1.0)
    assert cur.update() is None
    assert sp.version == 1


def test_curriculum_starved_scenario_accumulates_across_windows():
    """A floored low-weight scenario below min_rows per window must
    keep its evidence ACCUMULATING (not be reset), so once enough rows
    gather the weights can move back — adaptation is never one-way."""
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("rich:x=1 / poor:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, min_rows=8, adapt_params=False,
    )
    # three windows: rich has plenty, poor trickles 4 rows per window
    # at a HIGHER loss than rich
    for _ in range(3):
        led.account_batch(_stamped_batch("rich", 1, n=16), loss=0.1)
        led.account_batch(_stamped_batch("poor", 1, n=4), loss=2.0)
        cur.update()
    # by window 2 poor accumulated >= 8 rows: the update saw it and
    # moved weight toward the high-loss starved scenario
    assert sp.weights()["poor"] > 0.5
    assert sp.version >= 2


def test_curriculum_no_signal_means_no_version_churn():
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("a:x=1 / b:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, min_rows=2, adapt_params=False,
    )
    # tied losses: nothing to adapt — the space must NOT bump or
    # republish (per-version accounting would fragment over identical
    # spaces)
    led.account_batch(_stamped_batch("a", 1, n=8), loss=1.0)
    led.account_batch(_stamped_batch("b", 1, n=8), loss=1.0)
    assert cur.update() is None
    assert sp.version == 1


def test_curriculum_noop_cadence_keeps_evidence():
    """A no-op update (tied losses) must not consume the evidence
    windows: the next cadence still sees the accumulated history."""
    led = ScenarioAccounting()
    sp = ScenarioSpace.parse("a:x=1 / b:x=2")
    cur = ScenarioCurriculum(
        sp, ledger=led, every_steps=1, min_rows=4, adapt_params=False,
    )
    led.account_batch(_stamped_batch("a", 1, n=8), loss=1.0)
    led.account_batch(_stamped_batch("b", 1, n=8), loss=1.0)
    assert cur.update() is None  # tie: no-op, windows untouched
    # one differentiating batch later, the FULL history participates
    led.account_batch(_stamped_batch("b", 1, n=8), loss=3.0)
    report = cur.update()
    assert report is not None and sp.weights()["b"] > 0.5
    # windows were consumed by the real update
    assert led.window_losses(reset=False, min_rows=1) == {}


def test_cube_scene_scenario_draw_is_complete_not_a_delta():
    """apply_scenario reverts unnamed known params to defaults: a
    scenario without xy_jitter must NOT inherit the previous draw's
    noise (cross-scenario leakage flattens the loss gap the curriculum
    feeds on)."""
    from blendjax.producer.sim import CubeScene

    scene = CubeScene(shape=(16, 16), seed=0, half_extent=1.25)
    scene.apply_scenario({"xy_jitter": 9.0, "half_extent": 0.5})
    assert scene.xy_jitter == 9.0 and scene.half_extent == 0.5
    scene.apply_scenario({"half_extent": 0.7})
    assert scene.xy_jitter == 0.0  # reverted, not inherited
    scene.apply_scenario({})
    assert scene.half_extent == 1.25  # constructor default restored


def test_driver_strips_scenario_sidecar_before_jit():
    """Eager echo draws carry a host `_scenario_rows` sidecar (string/
    None leaves): TrainDriver.submit must strip it before the jitted
    step sees the batch — the scenario+echo+inflight combination."""
    import jax.numpy as jnp

    from blendjax.models import CubeRegressor
    from blendjax.train import (
        TrainDriver,
        make_supervised_step,
        make_train_state,
    )

    state = make_train_state(
        CubeRegressor(), np.zeros((4, 16, 16, 4), np.uint8)
    )
    driver = TrainDriver(
        make_supervised_step(), state, inflight=2, sync_every=1
    )
    batch = {
        "image": jnp.zeros((4, 16, 16, 4), jnp.uint8),
        "xy": jnp.zeros((4, 8, 2), jnp.float32),
        SCENARIO_ROWS_KEY: [{"id": "a", "ver": 1}, None, None, None],
        SCENARIO_KEY: {"id": "a", "ver": 1},
    }
    driver.submit(batch)
    driver.submit(batch)
    loss = driver.finish()[1]
    assert loss is not None and np.isfinite(loss)
    # the caller's batch keeps its sidecar (accounting reads it)
    assert SCENARIO_ROWS_KEY in batch


def test_applicator_sets_bounded_ack_send_timeout():
    import zmq

    from blendjax.producer import DuplexChannel
    from blendjax.producer.scenario import ScenarioApplicator

    chan = DuplexChannel("tcp://127.0.0.1:0", btid=0, allow_pickle=False)
    try:
        ScenarioApplicator(chan)
        # a mute consumer must cost a bounded send, never a wedged
        # render loop (the service-side channels carry the same bound)
        assert chan.sock.getsockopt(zmq.SNDTIMEO) == 500
    finally:
        chan.close()


def test_applicator_survives_malformed_control_message():
    from blendjax.producer import DuplexChannel
    from blendjax.producer.scenario import ScenarioApplicator
    from blendjax.transport import PairChannel

    chan = DuplexChannel("tcp://127.0.0.1:0", btid=0, allow_pickle=False)
    app = ScenarioApplicator(chan)
    peer = PairChannel(chan.addr, bind=False)
    try:
        # a pickle-bearing control payload (a set is not msgpack-able,
        # so it ships as an embedded pkl entry) must be REFUSED without
        # killing the producer's poll loop...
        peer.send(scenario_space={1, 2, 3})
        # ...and a well-formed space right behind it still lands
        peer.send(
            scenario_space=ScenarioSpace.parse("s:a=1").to_wire(),
            scenario_version=1,
        )
        assert app.wait_for_space(timeout_s=10)
        assert app.version == 1
    finally:
        peer.close()
        chan.close()


# ---------------------------------------------------------------------------
# replay / torch-compat handling of the stamp
# ---------------------------------------------------------------------------


def test_strip_stamps_keeps_scenario_for_replay_reaccounting():
    from blendjax.obs.lineage import strip_stamps

    msg = {
        "_seq": 4, "_pub_wall": 1.0, "_pub_mono": 2.0,
        "_trace": {"id": "x"}, SCENARIO_KEY: {"id": "s", "ver": 3},
        "image": 1,
    }
    out = strip_stamps(msg)
    # transport stamps die on replay; the CONTENT stamp survives so a
    # recorded stream re-accounts per scenario deterministically
    assert "_seq" not in out and "_trace" not in out
    assert out[SCENARIO_KEY] == {"id": "s", "ver": 3}


# ---------------------------------------------------------------------------
# fleet integration: membership changes keep the space consistent
# ---------------------------------------------------------------------------


class _StubScenarioService:
    def __init__(self):
        self.attached = []
        self.detached = []

    def attach(self, btid, addr):
        self.attached.append((btid, addr))

    def detach(self, btid):
        self.detached.append(btid)


class _StubLauncher:
    def __init__(self):
        self.n = 1

    def active_count(self):
        return self.n

    def active_indices(self):
        return list(range(self.n))

    def poll_processes(self):
        return [None] * self.n

    def add_instance(self, extra_args=None):
        i = self.n
        self.n += 1
        return i, {"DATA": f"tcp://127.0.0.1:9{i}00",
                   "CTRL": f"tcp://127.0.0.1:9{i}01"}

    def retire_instance(self, i, drain=True):
        self.n -= 1
        return {"DATA": f"tcp://127.0.0.1:9{i}00",
                "CTRL": f"tcp://127.0.0.1:9{i}01"}


class _StubConnector:
    def __init__(self):
        self.ops = []

    def connect(self, addr):
        self.ops.append(("connect", addr))

    def disconnect(self, addr):
        self.ops.append(("disconnect", addr))


class _StubLineage:
    def register(self, btid):
        pass

    def retire(self, btid):
        pass


def test_controller_attaches_scenario_before_data_connect():
    from blendjax.fleet import FleetController, FleetPolicy

    svc = _StubScenarioService()
    conn = _StubConnector()
    ctrl = FleetController(
        _StubLauncher(), connector=conn,
        policy=FleetPolicy(min_instances=1, max_instances=3, up_after=1,
                           cooldown_s=0.0),
        scenario_service=svc, lineage=_StubLineage(),
    )
    d = ctrl.tick(verdict="producer-bound", now=100.0)
    assert d["action"] == "scale_up"
    assert svc.attached == [(1, "tcp://127.0.0.1:9101")]
    # scenario BEFORE data: the newcomer held the space before ingest
    # could count one of its frames
    assert conn.ops == [("connect", "tcp://127.0.0.1:9100")]
    # scale down detaches the duplex channel at retire time
    d = ctrl.tick(verdict="step-bound", now=200.0)
    d = ctrl.tick(verdict="step-bound", now=300.0)
    d = ctrl.tick(verdict="step-bound", now=400.0)
    d = ctrl.tick(verdict="step-bound", now=500.0)
    assert d["action"] == "scale_down"
    assert svc.detached == [1]


def test_controller_remote_admission_attaches_ctrl_addr():
    from blendjax.fleet import FleetController

    svc = _StubScenarioService()
    conn = _StubConnector()
    ctrl = FleetController(
        _StubLauncher(), connector=conn, scenario_service=svc,
        lineage=_StubLineage(),
    )
    r = ctrl.admit_remote(
        "box-1", "tcp://10.0.0.5:5555",
        telemetry={"ctrl_addr": "tcp://10.0.0.5:5556"},
    )
    assert r["ok"]
    assert svc.attached == [("box-1", "tcp://10.0.0.5:5556")]
    ctrl.retire_remote("box-1", now=0.0)
    assert svc.detached == ["box-1"]


@pytest.mark.slow
def test_mid_run_scale_up_newcomer_holds_current_version():
    """The satellite contract: a mid-run scale-up's newcomer receives
    the CURRENT space version before its first frame is counted."""
    from blendjax.data import RemoteStream
    from blendjax.fleet import FleetController, FleetPolicy, synthetic_fleet

    sp = ScenarioSpace.parse("a:half_extent=u(0.8,1.2) / b:xy_jitter=4")
    sp.bump()  # current version is 2, not the default 1
    svc = ScenarioService(sp)
    try:
        with synthetic_fleet(
            1, shape=(32, 32), batch=4, rate=40, scenario=True,
            bind_grace_s=0.5,
        ) as launcher:
            svc.attach(0, launcher.addresses["CTRL"][0])
            assert svc.wait_acked(timeout=15), svc.state()
            stream = RemoteStream(
                list(launcher.addresses["DATA"]), timeoutms=20_000,
                copy_arrays=True,
            )
            it = iter(stream)
            assert next(it)[SCENARIO_KEY]["ver"] == 2
            ctrl = FleetController(
                launcher, connector=stream,
                policy=FleetPolicy(min_instances=1, max_instances=2,
                                   up_after=1, cooldown_s=0.0),
                scenario_service=svc, respawn_dead=False,
            )
            d = ctrl.tick(verdict="producer-bound")
            assert d["action"] == "scale_up"
            assert svc.wait_acked(version=2, timeout=15), svc.state()
            # EVERY frame the newcomer publishes carries the current
            # version (it held publishing until the space arrived)
            deadline = time.monotonic() + 20
            saw_newcomer = False
            while not saw_newcomer and time.monotonic() < deadline:
                msg = next(it)
                assert msg[SCENARIO_KEY]["ver"] == 2, msg[SCENARIO_KEY]
                if msg.get("btid") == 1:
                    saw_newcomer = True
            assert saw_newcomer
    finally:
        svc.stop()


# ---------------------------------------------------------------------------
# end-to-end: synthetic fleet, exact per-scenario histograms
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_e2e_synthetic_fleet_exact_per_scenario_histograms(monkeypatch):
    import blendjax.data.echo as echo_mod
    from blendjax.data import EchoingPipeline, StreamDataPipeline
    from blendjax.fleet import synthetic_fleet

    led = ScenarioAccounting()
    monkeypatch.setattr(echo_mod, "scenario_accounting", led)
    sp = ScenarioSpace.parse(
        "easy:half_extent=u(0.8,1.2) / hard:xy_jitter=g(6,0.5)"
    )
    svc = ScenarioService(sp)
    try:
        with synthetic_fleet(
            2, shape=(32, 32), batch=4, rate=60, scenario=True,
            bind_grace_s=0.5,
        ) as launcher:
            for i, addr in enumerate(launcher.addresses["CTRL"]):
                svc.attach(i, addr)
            assert svc.wait_acked(timeout=15), svc.state()
            led.declare(sp)
            pipe = StreamDataPipeline(
                launcher.addresses["DATA"], batch_size=8,
                timeoutms=30_000,
            )
            echo = EchoingPipeline(
                pipe, capacity=64, max_echo_factor=4, augment=None
            )
            steps = 0
            with echo:
                for b in echo:
                    led.observe_loss(
                        b[SCENARIO_ROWS_KEY], 0.5 + 0.01 * steps
                    )
                    steps += 1
                    if steps >= 25:
                        break
            totals = led.totals()
            assert set(totals) == {"easy", "hard"}
            assert sum(f + e for f, e in totals.values()) == steps * 8
            rep = led.report()
            for sid in ("easy", "hard"):
                s = rep["scenarios"][sid]
                # loss histogram count == rows scored, exactly
                assert s["loss"]["count"] == s["rows"]
                assert s["declared"]
                assert set(s["versions"]) == {1}
    finally:
        svc.stop()
