"""Sharded parallel ingest: partitioning, lock-cheap parallel assembly,
worker-pool semantics, and the single-vs-sharded CPU microbench."""

import os
import threading
import time

import numpy as np
import pytest

from blendjax.data import (
    HostIngest,
    ParallelBatchAssembler,
    RemoteStream,
    ShardedHostIngest,
    StreamSchema,
    partition_addresses,
)
from blendjax.data.schema import SchemaError
from blendjax.transport import DataPublisherSocket
from blendjax.transport.wire import decode_message, encode_message

WILD = "tcp://127.0.0.1:*"


def _item(i, h=4, w=6):
    return {
        "btid": 0,
        "image": np.full((h, w, 4), i % 255, np.uint8),
        "xy": np.full((8, 2), float(i), np.float32),
        "frameid": i,
    }


# -- shard partitioning ------------------------------------------------------


def test_partition_addresses_round_robin():
    assert partition_addresses(["a", "b", "c", "d", "e"], 2) == [
        ["a", "c", "e"], ["b", "d"],
    ]
    assert partition_addresses(["a", "b", "c"], 3) == [["a"], ["b"], ["c"]]


def test_partition_addresses_clamps_to_fleet_size():
    # never more shards than producers, never an empty shard
    assert partition_addresses(["a", "b"], 8) == [["a"], ["b"]]
    assert partition_addresses("tcp://one", 4) == [["tcp://one"]]
    assert partition_addresses(["a", "b", "c"], 0) == [["a", "b", "c"]]


# -- parallel assembly -------------------------------------------------------


def test_parallel_assembler_no_lost_or_duplicated_slots():
    """4 writer threads x 100 items through reserve/write: every item
    lands in exactly one slot of exactly one batch (ids recorded at
    emit time — the bounded-queue contract)."""
    schema = StreamSchema.infer(_item(0))
    asm = ParallelBatchAssembler(schema, batch_size=8, num_buffers=8)
    seen = []
    lock = threading.Lock()

    def writer(lo, hi):
        for i in range(lo, hi):
            pending, slot = asm.reserve()
            batch = asm.write(pending, slot, _item(i))
            if batch is not None:
                with lock:
                    seen.extend(int(v) for v in batch["frameid"])
                    seen_meta.append(len(batch["_meta"]))

    seen_meta = []
    threads = [
        threading.Thread(target=writer, args=(k * 100, (k + 1) * 100))
        for k in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(seen) == list(range(400))
    assert seen_meta == [8] * 50  # every batch carried full _meta


def test_parallel_assembler_flush_partial():
    schema = StreamSchema.infer(_item(0))
    asm = ParallelBatchAssembler(schema, batch_size=4, num_buffers=3)
    assert asm.flush() is None
    for i in range(3):
        assert asm.add(_item(i)) is None
    tail = asm.flush()
    assert tail["_partial"] is True
    assert [int(v) for v in tail["frameid"]] == [0, 1, 2]
    assert len(tail["_meta"]) == 3
    assert asm.flush() is None  # flush is one-shot


# -- worker pool over plain iterables ---------------------------------------


def test_sharded_ingest_counts_and_partial_final():
    streams = [[_item(i) for i in range(k, 60, 3)] for k in range(3)]
    ingest = ShardedHostIngest(
        streams, batch_size=8, emit_partial_final=True
    )
    # consume incrementally: batch buffers recycle (pool contract, same
    # as the serial BatchAssembler) so a test must not retain them all
    got, partial_sizes = [], []
    for b in ingest:
        got.extend(int(v) for v in b["frameid"])
        if b.get("_partial"):
            partial_sizes.append(len(b["frameid"]))
    assert sorted(got) == list(range(60))
    assert ingest.items_in == 60
    assert partial_sizes == [60 % 8]


def test_sharded_ingest_drops_tail_without_opt_in():
    streams = [[_item(i) for i in range(k, 30, 2)] for k in range(2)]
    batches = list(ShardedHostIngest(streams, batch_size=8))
    assert sum(len(b["frameid"]) for b in batches) == 24  # 30 - (30 % 8)
    assert not any(b.get("_partial") for b in batches)


def test_sharded_ingest_propagates_shard_error():
    bad = dict(_item(1))
    bad["image"] = np.zeros((9, 9, 4), np.uint8)
    ingest = ShardedHostIngest(
        [[_item(0)], [_item(2), bad]], batch_size=2
    )
    with pytest.raises(SchemaError):
        list(ingest)


# -- worker pool over real sockets ------------------------------------------


def _publish_async(pub, items):
    t = threading.Thread(
        target=lambda: [pub.publish(**it) for it in items], daemon=True
    )
    t.start()
    return t


def test_sharded_ingest_two_producers_two_shards():
    pubs = [DataPublisherSocket(WILD, btid=k) for k in range(2)]
    feeders = [
        _publish_async(pub, [_item(k * 20 + i) for i in range(20)])
        for k, pub in enumerate(pubs)
    ]
    shards = partition_addresses([p.addr for p in pubs], 2)
    streams = [
        RemoteStream(
            shard, timeoutms=5000, max_items=40,
            worker_index=i, num_workers=2,
        )
        for i, shard in enumerate(shards)
    ]
    ingest = ShardedHostIngest(streams, batch_size=8)
    got = sorted(int(v) for b in ingest for v in b["frameid"])
    assert got == list(range(40))
    for t in feeders:
        t.join(timeout=10)
    for p in pubs:
        p.close()


def test_sharded_ingest_stop_responsive_under_long_timeout():
    """stop() must return promptly even while every worker is parked in
    a long recv (the request_stop poll-slice path), and must not leave
    live threads behind."""
    pub = DataPublisherSocket(WILD, btid=0)
    streams = [RemoteStream([pub.addr], timeoutms=60_000) for _ in range(2)]
    ingest = ShardedHostIngest(streams, batch_size=4).start()
    time.sleep(0.6)  # both workers are inside the sliced poll now
    t0 = time.monotonic()
    ingest.stop()
    assert time.monotonic() - t0 < 5.0
    assert not any(t.is_alive() for t in ingest._threads)
    pub.close()


def test_pipeline_ingest_workers_integration():
    """StreamDataPipeline(ingest_workers=2) over two producers: the
    sharded pool feeds the same device pipeline, nothing lost."""
    from blendjax.data import StreamDataPipeline

    pubs = [DataPublisherSocket(WILD, btid=k) for k in range(2)]
    feeders = [
        _publish_async(pub, [_item(k * 16 + i) for i in range(16)])
        for k, pub in enumerate(pubs)
    ]
    with StreamDataPipeline(
        [p.addr for p in pubs], batch_size=8, ingest_workers=2,
        timeoutms=5000, max_items=32,
    ) as pipe:
        got = sorted(
            int(v) for b in pipe for v in np.asarray(b["frameid"])
        )
    assert got == list(range(32))
    assert isinstance(pipe.ingest, ShardedHostIngest)
    for t in feeders:
        t.join(timeout=10)
    for p in pubs:
        p.close()


def test_pipeline_single_worker_keeps_host_ingest():
    from blendjax.data import StreamDataPipeline

    pub = DataPublisherSocket(WILD, btid=0)
    feeder = _publish_async(pub, [_item(i) for i in range(8)])
    with StreamDataPipeline(
        [pub.addr], batch_size=4, timeoutms=5000, max_items=8
    ) as pipe:
        got = sorted(
            int(v) for b in pipe for v in np.asarray(b["frameid"])
        )
    assert got == list(range(8))
    assert isinstance(pipe.ingest, HostIngest)  # default path unchanged
    feeder.join(timeout=10)
    pub.close()
    # a single producer can't shard: ingest_workers=2 falls back (a
    # FRESH publisher — reusing the first one races its dying PULL
    # pipe, which is the at-most-once contract, not a bug here)
    pub2 = DataPublisherSocket(WILD, btid=1)
    feeder2 = _publish_async(pub2, [_item(i) for i in range(8)])
    with StreamDataPipeline(
        [pub2.addr], batch_size=4, ingest_workers=2,
        timeoutms=5000, max_items=8,
    ) as pipe:
        list(pipe)
    assert isinstance(pipe.ingest, HostIngest)
    feeder2.join(timeout=10)
    pub2.close()


def test_pipeline_sharded_max_items_is_global_across_unequal_shards():
    """max_items is enforced as ONE pool-wide budget, not an even
    per-shard split: shards see disjoint producer subsets, so a split
    would block one shard on messages only the other shard's producers
    hold (and silently strand the surplus)."""
    from blendjax.data import StreamDataPipeline

    pubs = [DataPublisherSocket(WILD, btid=k) for k in range(2)]
    counts = [24, 8]  # a 16/16 split would strand 8 and time out on 8
    feeders = [
        _publish_async(pub, [_item(k * 100 + i) for i in range(counts[k])])
        for k, pub in enumerate(pubs)
    ]
    with StreamDataPipeline(
        [p.addr for p in pubs], batch_size=8, ingest_workers=2,
        timeoutms=8000, max_items=32,
    ) as pipe:
        got = [int(v) for b in pipe for v in np.asarray(b["frameid"])]
    assert sorted(got) == sorted(
        list(range(24)) + [100 + i for i in range(8)]
    )
    for t in feeders:
        t.join(timeout=10)
    for p in pubs:
        p.close()


def test_wire_counters_scoped_to_data_stream():
    """Control/RPC channels decode through the same codec but must not
    pollute the wire.raw/compressed byte pair the bench publishes."""
    from blendjax.transport import PairChannel
    from blendjax.utils.metrics import metrics

    metrics.reset()
    prod = PairChannel(WILD, btid=1, bind=True)
    cons = PairChannel(prod.addr, btid=None, bind=False)
    cons.send(params=np.zeros((64, 64), np.float32))
    got = prod.recv(timeoutms=5000)
    assert got is not None and got["params"].shape == (64, 64)
    assert not any(k.startswith("wire.") for k in metrics.counters)
    prod.close(); cons.close()

    pub = DataPublisherSocket(WILD, btid=0)
    feeder = _publish_async(pub, [_item(0)])
    stream = RemoteStream([pub.addr], timeoutms=5000, max_items=1)
    list(stream)
    feeder.join(timeout=10)
    assert metrics.counters["wire.raw_bytes"] > 0  # data stream counts
    pub.close()


def test_pipeline_rejects_worker_kwargs_with_sharding():
    from blendjax.data import StreamDataPipeline

    with pytest.raises(ValueError, match="worker"):
        StreamDataPipeline(
            ["tcp://a", "tcp://b"], batch_size=4, ingest_workers=2,
            num_workers=2,
        )


# -- the microbench: sharded beats single-threaded ---------------------------


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 2, reason="needs >=2 cores to show overlap"
)
def test_sharded_ingest_outpaces_single_worker():
    """CPU-only microbench (acceptance criterion): >=2 producers'
    decode work (zlib "ndz" inflate + memcpy, both GIL-releasing)
    overlaps across 2 shards, so the pool's items/s beats the
    single-thread path on the same message set. In-process streams
    (pre-encoded wire frames, decoded inside the iterator) keep the
    work deterministic — the bench covers the socket layer."""
    rng = np.random.default_rng(0)
    base = np.repeat(rng.integers(0, 50, 65536, dtype=np.uint8), 16)
    n_msgs, n_shards = 48, 2

    def wire(i):
        return [
            bytes(f) for f in encode_message(
                {
                    "btid": i % n_shards,
                    "image": np.roll(base, i).reshape(1024, 1024),
                    "frameid": i,
                },
                compress_level=1, compress_min_bytes=1024,
            )
        ]

    messages = [wire(i) for i in range(n_msgs)]

    def decoding_stream(msgs):
        for frames in msgs:
            yield dict(decode_message(frames))

    def run_once(sharded: bool) -> float:
        if sharded:
            shards = [messages[k::n_shards] for k in range(n_shards)]
            ingest = ShardedHostIngest(
                [decoding_stream(s) for s in shards], batch_size=8,
                prefetch=4,
            )
        else:
            ingest = HostIngest(
                decoding_stream(messages), batch_size=8, prefetch=4
            )
        t0 = time.perf_counter()
        n = sum(len(b["frameid"]) for b in ingest)
        dt = time.perf_counter() - t0
        assert n == n_msgs
        return n / dt

    # best-of-2 each, interleaved, so a scheduler hiccup on one pass
    # can't decide the comparison
    single = max(run_once(False), run_once(False))
    sharded = max(run_once(True), run_once(True))
    assert sharded > single, (
        f"sharded pool ({sharded:.1f} items/s) should beat the single "
        f"worker ({single:.1f} items/s) with {n_shards} shards of "
        "GIL-releasing decode work"
    )


# -- shared inflate pool (decode-ahead) --------------------------------------


def test_shared_inflate_pool_wires_streams_and_preserves_content():
    """The pool attaches one shared executor to every shard stream
    (RemoteStream.set_inflate_pool), each stream pipelines decode-ahead
    over real sockets with per-producer ordering intact, and stop()
    shuts the executor down."""
    from blendjax.data.stream import RemoteStream
    from blendjax.utils.metrics import metrics as reg

    reg.reset()
    pubs = [
        DataPublisherSocket(
            "tcp://127.0.0.1:*", btid=i, compress_level=6,
            compress_min_bytes=1024,
        )
        for i in range(2)
    ]
    ramp = np.tile(np.arange(64, dtype=np.uint8), 1024).reshape(256, 256)
    n_per = 8

    def feed():
        for i in range(n_per):
            for p in pubs:
                p.publish(image=ramp + (i % 4), frameid=i)

    streams = [
        RemoteStream([p.addr], timeoutms=8000, max_items=n_per)
        for p in pubs
    ]
    ingest = ShardedHostIngest(streams, batch_size=4, inflate_workers=2)
    t = threading.Thread(target=feed)
    t.start()
    got = list(ingest)
    t.join()
    assert ingest._inflate_pool is None  # shut down with the workers
    assert sum(len(b["frameid"]) for b in got) == 2 * n_per
    for b in got:
        for row, fid in zip(b["image"], b["frameid"]):
            np.testing.assert_array_equal(row, ramp + (int(fid) % 4))
    counters = reg.report()["counters"]
    assert counters.get("wire.pool_decodes", 0) == 2 * n_per
    # per-producer arrival order == publish order (FIFO futures): the
    # lineage seq tracker saw no reorders/gaps
    assert counters.get("wire.seq_gaps", 0) == 0
    assert counters.get("wire.seq_reorders", 0) == 0
    for p in pubs:
        p.close()


def test_inflate_workers_zero_keeps_inline_decode():
    from blendjax.data.stream import RemoteStream
    from blendjax.utils.metrics import metrics as reg

    reg.reset()
    pub = DataPublisherSocket(
        "tcp://127.0.0.1:*", btid=0, compress_level=6,
        compress_min_bytes=1024,
    )
    ramp = np.tile(np.arange(64, dtype=np.uint8), 1024)
    stream = RemoteStream([pub.addr], timeoutms=8000, max_items=3)
    ingest = ShardedHostIngest(
        [stream], batch_size=3, inflate_workers=0
    )
    t = threading.Thread(
        target=lambda: [pub.publish(image=ramp, frameid=i) for i in range(3)]
    )
    t.start()
    got = list(ingest)
    t.join()
    assert sum(len(b["frameid"]) for b in got) == 3
    assert ingest._inflate_pool is None
    assert reg.report()["counters"].get("wire.pool_decodes", 0) == 0
    pub.close()


def test_decode_ahead_never_over_receives_past_max_items():
    """The opportunistic non-blocking fill is gated on the remaining
    budget: with more messages parked on the socket than max_items,
    the stream submits EXACTLY max_items decodes — an over-received
    message would be consumed off the socket but never yielded, teed,
    or lineage-ingested."""
    from concurrent.futures import ThreadPoolExecutor

    from blendjax.data.stream import RemoteStream
    from blendjax.utils.metrics import metrics as reg

    import zmq

    reg.reset()
    pub = DataPublisherSocket(
        "tcp://127.0.0.1:*", btid=0, send_hwm=64, compress_level=6,
        compress_min_bytes=1024,
    )
    # Bounded sends: once the consumer takes its max_items and closes,
    # the PUSH socket re-enters mute state and an untimed send of the
    # surplus tail would wedge this feeder FOREVER (the BJX119 hazard,
    # in a test) — t.join() then hung the whole suite on slow boxes.
    pub.sock.setsockopt(zmq.SNDTIMEO, 2000)
    ramp = np.tile(np.arange(64, dtype=np.uint8), 1024)
    n = 5

    def feed():
        for i in range(n + 3):
            try:
                pub.publish(image=ramp, frameid=i)
            except zmq.Again:
                return  # consumer gone: the surplus tail is moot

    stream = RemoteStream([pub.addr], timeoutms=8000, max_items=n)
    pool = ThreadPoolExecutor(2)
    stream.set_inflate_pool(pool)
    t = threading.Thread(target=feed)
    t.start()
    got = list(stream)
    t.join(timeout=10.0)
    assert not t.is_alive(), "feeder wedged in a mute-state send"
    assert [int(m["frameid"]) for m in got] == list(range(n))
    counters = reg.report()["counters"]
    assert counters.get("wire.pool_decodes", 0) == n, counters
    pool.shutdown()
    pub.close()


def test_inflate_pool_teardown_is_single_sided_after_stop():
    """PR 13 follow-up, pinned by BJX117: the stop()-vs-last-worker
    pool swap now runs under _active_lock on BOTH sides — whichever
    side wins, exactly one shutdown happens, the handle is gone, and a
    second stop() stays a no-op."""

    class HookableEmpty:
        """Minimal shard stream: accepts the shared pool, yields
        nothing (so the last worker's teardown arm runs too)."""

        def __init__(self):
            self.pool = None

        def set_inflate_pool(self, pool):
            self.pool = pool

        def __iter__(self):
            return iter([])

    streams = [HookableEmpty(), HookableEmpty()]
    ingest = ShardedHostIngest(streams, batch_size=2, inflate_workers=2)
    ingest.start()
    assert streams[0].pool is not None  # the pool really was built
    list(ingest)  # drain to _DONE: the last worker tears down its side
    ingest.stop()
    assert ingest._inflate_pool is None
    ingest.stop()  # idempotent second teardown
    assert ingest._inflate_pool is None
