"""Shared-memory ring transport tests (zero-copy local descriptors).

Covers the seqlock generation protocol (torn writes detected, never
mis-counted as drops), the ack-based backpressure/reclaim path, the
publisher's descriptor encoding + stream-side resolution, crash safety
(a kill -9'd producer mid-slot-write), the launcher registry's
exactly-once unlink, resource_tracker hygiene, and f32 loss equality
between the shm path and the compressed wire on identical content.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from blendjax.obs.lineage import lineage
from blendjax.transport import DataPublisherSocket
from blendjax.transport.shm import (
    REGISTRY_ENV,
    ShmCapacityError,
    ShmRing,
    attach_ring,
    detach_all,
    reap_registry,
    resolve_message,
    unlink_segment,
)
from blendjax.utils.metrics import metrics

WILD = "tcp://127.0.0.1:*"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean():
    metrics.reset()
    lineage.reset()
    yield
    detach_all()
    metrics.reset()
    lineage.reset()


def _counters():
    return metrics.report()["counters"]


def _fields(i):
    return {
        "image": np.full((4, 6, 4), i % 255, np.uint8),
        "xy": np.full((8, 2), float(i), np.float32),
    }


# -- ring protocol ------------------------------------------------------------


def test_ring_roundtrip_and_generation_protocol():
    with ShmRing(slots=3, slot_bytes=4096) as ring:
        descs = [ring.write(_fields(i)) for i in range(3)]
        for i, desc in enumerate(descs):
            assert desc["n"] == ring.name and desc["s"] == i
            assert desc["g"] % 2 == 0  # descriptors carry stable gens
            out = ring.read(desc)
            np.testing.assert_array_equal(out["image"], _fields(i)["image"])
            np.testing.assert_array_equal(out["xy"], _fields(i)["xy"])
        # acked slots are immediately reusable: a full second lap with
        # a live reader never waits, never reclaims
        for i in range(3):
            out = ring.read(ring.write(_fields(10 + i)))
            assert out["xy"][0, 0] == float(10 + i)
        assert ring.reclaims == 0


def test_oversize_payload_rejected_before_touching_generation():
    with ShmRing(slots=2, slot_bytes=64) as ring:
        with pytest.raises(ShmCapacityError):
            ring.write({"image": np.zeros((64, 64, 4), np.uint8)})
        # the failed write never tore a slot
        assert int(ring._gen[0]) == 0 and int(ring._gen[1]) == 0
        # small payloads still fit the same ring
        out = ring.read(ring.write({"a": np.arange(4, dtype=np.int32)}))
        np.testing.assert_array_equal(out["a"], np.arange(4, dtype=np.int32))


def test_torn_generation_detected_on_read():
    with ShmRing(slots=2, slot_bytes=4096) as ring:
        desc = ring.write(_fields(1))
        ring.begin_write(desc["s"])  # writer "dies" mid-copy: odd gen
        assert ring.read(desc) is None
        ring.end_write(desc["s"])  # a later writer finished the slot
        assert ring.read(desc) is None  # gen advanced past the descriptor
        # out-of-range slots (corrupt descriptor) are torn, not a crash
        assert ring.read({"n": ring.name, "s": 99, "g": 2, "f": []}) is None


def test_unacked_slot_reclaimed_after_timeout():
    with ShmRing(slots=1, slot_bytes=4096) as ring:
        stale = ring.write(_fields(0))  # never read, never acked
        t0 = time.monotonic()
        fresh = ring.write(_fields(1), timeout_s=0.05)
        assert time.monotonic() - t0 >= 0.05
        assert ring.reclaims == 1
        assert _counters().get("wire.shm_reclaims") == 1
        # the stale descriptor fails its generation check; the fresh
        # one reads clean
        assert ring.read(stale) is None
        assert ring.read(fresh)["xy"][0, 0] == 1.0
        assert _counters().get("wire.shm_torn") is None  # read(), not resolve


# -- descriptor resolution ----------------------------------------------------


def test_resolve_message_merges_fields_and_counts():
    ring = ShmRing(slots=2, slot_bytes=4096)
    try:
        desc = ring.write(_fields(7))
        msg = {"frameid": 7, "_seq": 0, "_shm": desc}
        out = resolve_message(msg)
        assert out is msg and "_shm" not in out
        np.testing.assert_array_equal(out["image"], _fields(7)["image"])
        c = _counters()
        assert c.get("wire.shm_reads") == 1
        assert c.get("wire.shm_bytes") == _fields(7)["image"].nbytes + \
            _fields(7)["xy"].nbytes
        assert c.get("wire.shm_torn") is None
    finally:
        detach_all()
        ring.close()
        ring.unlink()


def test_resolve_message_marks_torn_and_keeps_stamps():
    ring = ShmRing(slots=2, slot_bytes=4096)
    try:
        desc = ring.write(_fields(3))
        ring.begin_write(desc["s"])
        msg = {"frameid": 3, "_seq": 5, "_shm": desc}
        out = resolve_message(msg)
        # payload discarded, stamps intact, marker set, counted exactly
        assert out.get("_shm_torn") is True and "image" not in out
        assert out["_seq"] == 5
        assert _counters().get("wire.shm_torn") == 1
    finally:
        detach_all()
        ring.close()
        ring.unlink()


def test_resolve_message_vanished_segment_is_torn():
    msg = {"_seq": 0, "_shm": {"n": "bjx-gone-xyz", "s": 0, "g": 2, "f": []}}
    out = resolve_message(msg)
    assert out.get("_shm_torn") is True
    assert _counters().get("wire.shm_torn") == 1
    # second resolve hits the cached attach failure, still counts
    resolve_message({"_seq": 1, "_shm": {"n": "bjx-gone-xyz", "s": 0,
                                         "g": 2, "f": []}})
    assert _counters().get("wire.shm_torn") == 2


# -- publisher + stream end to end --------------------------------------------


def test_publisher_shm_end_to_end_zero_copy():
    from blendjax.data import RemoteStream

    pub = DataPublisherSocket(WILD, btid=0, shm=4)
    n = 12
    items = [
        {"frameid": i, **_fields(i)} for i in range(n)
    ]
    t = threading.Thread(
        target=lambda: [pub.publish(**it) for it in items], daemon=True
    )
    t.start()
    got = list(RemoteStream([pub.addr], max_items=n, timeoutms=8000))
    t.join(timeout=10)
    try:
        assert [m["frameid"] for m in got] == list(range(n))
        for i, m in enumerate(got):
            np.testing.assert_array_equal(m["image"], _fields(i)["image"])
            np.testing.assert_array_equal(m["xy"], _fields(i)["xy"])
        c = _counters()
        assert c.get("wire.shm_reads") == n
        assert c.get("wire.shm_torn") is None
        assert c.get("wire.seq_gaps", 0) == 0
    finally:
        detach_all()
        pub.close()


def test_publisher_oversize_falls_back_to_wire():
    from blendjax.data import RemoteStream

    ring = ShmRing(slots=2, slot_bytes=64)
    pub = DataPublisherSocket(WILD, btid=0, shm=ring)
    big = {"frameid": 0, "image": np.arange(64 * 64 * 4,
                                            dtype=np.uint8).reshape(64, 64, 4)}
    t = threading.Thread(target=lambda: pub.publish(**big), daemon=True)
    t.start()
    got = list(RemoteStream([pub.addr], max_items=1, timeoutms=8000))
    t.join(timeout=10)
    try:
        np.testing.assert_array_equal(got[0]["image"], big["image"])
        c = _counters()
        assert c.get("wire.shm_fallbacks") == 1
        assert c.get("wire.shm_reads") is None
    finally:
        detach_all()
        pub.close()
        ring.close()
        ring.unlink()


_KILLED_PRODUCER = """\
import json, os, signal, sys
import numpy as np
from blendjax.transport import DataPublisherSocket
from blendjax.transport.shm import ShmRing

ring = ShmRing(slots=4, slot_bytes=1 << 16)
pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0, shm=ring)
print(json.dumps({"addr": pub.addr, "ring": ring.name}), flush=True)
for i in range(4):
    pub.publish(
        frameid=i,
        image=np.full((4, 6, 4), i, np.uint8),
        xy=np.full((8, 2), float(i), np.float32),
    )
sys.stdin.readline()          # parent signals: consumer connected + drained
ring.begin_write(2)           # die mid-copy of a slot-2 rewrite
os.kill(os.getpid(), signal.SIGKILL)
"""


def test_killed_producer_mid_write_skips_torn_with_exact_accounting():
    """kill -9 the producer mid-slot-write: the reader skips exactly the
    torn generation (`wire.shm_torn == 1`), delivers everything else,
    and seq accounting shows zero gaps — the stamps rode the wire."""
    from blendjax.data import RemoteStream

    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop(REGISTRY_ENV, None)  # standalone producer, parent reaps
    proc = subprocess.Popen(
        [sys.executable, "-c", _KILLED_PRODUCER],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, cwd=REPO, env=env,
    )
    ring_name = None
    try:
        info = json.loads(proc.stdout.readline())
        ring_name = info["ring"]
        stream = RemoteStream([info["addr"]], max_items=3, timeoutms=10000)
        it = iter(stream)
        first = next(it)  # connects the PULL side; io thread drains the rest
        time.sleep(0.5)   # let messages 1..3 land in our zmq buffer
        proc.stdin.write(b"go\n")
        proc.stdin.flush()
        proc.wait(timeout=10)
        assert proc.returncode == -signal.SIGKILL
        got = [first] + list(it)
        # message 2's slot was torn by the dying writer: skipped, not a gap
        assert [m["frameid"] for m in got] == [0, 1, 3]
        c = _counters()
        assert c.get("wire.shm_torn") == 1
        assert c.get("wire.shm_reads") == 3
        assert c.get("wire.seq_gaps", 0) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
        detach_all()
        if ring_name:
            unlink_segment(ring_name)


# -- registry / launcher hygiene ----------------------------------------------


def test_registry_reap_unlinks_exactly_once(tmp_path, monkeypatch):
    reg = str(tmp_path / "shm-reg")
    monkeypatch.setenv(REGISTRY_ENV, reg)
    r1 = ShmRing(slots=1, slot_bytes=64, btid=1)
    r2 = ShmRing(slots=1, slot_bytes=64, btid=1)
    r3 = ShmRing(slots=1, slot_bytes=64, btid=2)
    names = [r.name for r in (r1, r2, r3)]
    assert len(os.listdir(reg)) == 3
    # retire btid 1: its two segments go, btid 2's stays attachable
    assert reap_registry(reg, btid=1) == 2
    assert attach_ring(names[0]) is None and attach_ring(names[1]) is None
    assert ShmRing.attach(names[2]).name == names[2]
    # second pass is a no-op: markers were consumed with the unlink
    assert reap_registry(reg, btid=1) == 0
    # full teardown reaps the rest; a third pass finds nothing
    assert reap_registry(reg) == 1
    assert reap_registry(reg) == 0
    assert os.listdir(reg) == []
    for r in (r1, r2, r3):
        r.close()
        r.unlink()  # idempotent: already reaped externally, must not raise
    detach_all()


def test_publisher_owned_ring_unlinks_on_close_without_registry():
    pub = DataPublisherSocket(WILD, btid=0, shm=2)
    from blendjax.data import RemoteStream

    t = threading.Thread(
        target=lambda: pub.publish(frameid=0, **_fields(0)), daemon=True
    )
    t.start()
    got = list(RemoteStream([pub.addr], max_items=1, timeoutms=8000))
    t.join(timeout=10)
    name = pub._shm_ring.name
    detach_all()
    pub.close()  # no registry: the owning publisher unlinks its ring
    assert got[0]["frameid"] == 0
    with pytest.raises(FileNotFoundError):
        ShmRing.attach(name)


def test_no_resource_tracker_leak_warnings():
    """Create, attach, unlink, and exit: the resource_tracker must stay
    silent (no leaked shared_memory warnings, no KeyError noise)."""
    code = (
        "from blendjax.transport.shm import ShmRing, unlink_segment\n"
        "import numpy as np\n"
        "r = ShmRing(slots=2, slot_bytes=4096)\n"
        "d = r.write({'a': np.arange(8, dtype=np.float32)})\n"
        "c = ShmRing.attach(r.name)\n"
        "assert c.read(d) is not None\n"
        "c.close()\n"
        "r.close()\n"
        "r.unlink()\n"
        "r2 = ShmRing(slots=1, slot_bytes=64)\n"
        "r2.close()\n"
        "assert unlink_segment(r2.name)\n"
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    env.pop(REGISTRY_ENV, None)
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO, env=env, timeout=120,
    )
    assert res.returncode == 0, res.stderr
    assert "resource_tracker" not in res.stderr
    assert "leaked" not in res.stderr


def test_fleet_shm_wire_through_launcher_with_registry_reap():
    """`synthetic --wire shm` under the real launcher: batches arrive
    through the ring, the launcher's registry tracks the segment, and
    `retire_instance` unlinks it exactly once."""
    from blendjax.data import RemoteStream
    from blendjax.fleet import synthetic_fleet

    with synthetic_fleet(
        1, shape=(16, 16), batch=8, frames=-1,
        extra_args=["--wire", "shm"],
    ) as ln:
        stream = RemoteStream(
            [ln.instance_sockets(0)["DATA"]], max_items=6, timeoutms=15000,
        )
        got = list(stream)
        assert len(got) == 6
        for m in got:
            assert m["image"].shape == (8, 16, 16, 4)
        assert _counters().get("wire.shm_reads", 0) >= 1
        assert _counters().get("wire.seq_gaps", 0) == 0
        registry = ln._shm_registry
        assert registry and os.path.isdir(registry)
        markers = [fn for fn in os.listdir(registry) if "__" in fn]
        assert len(markers) == 1
        seg = markers[0].partition("__")[2]
        ln.retire_instance(0)
        # the retire reaped marker + segment; a second unlink is a no-op
        assert [fn for fn in os.listdir(registry) if "__" in fn] == []
        assert unlink_segment(seg) is False


# -- numerical equality: shm vs compressed wire -------------------------------


def test_f32_loss_equality_shm_vs_ndz():
    """The same recorded content through the shm ring and through the
    ndz wire codec must produce bitwise-identical f32 losses."""
    import optax

    from blendjax.data import RemoteStream
    from blendjax.models import CubeRegressor
    from blendjax.train import make_supervised_step, make_train_state

    rng = np.random.default_rng(11)
    items = [
        {
            "frameid": i,
            "image": rng.integers(0, 255, (16, 16, 4), np.uint8),
            "xy": (rng.random((8, 2)) * 16).astype(np.float32),
        }
        for i in range(8)
    ]

    def _collect(**pub_kwargs):
        pub = DataPublisherSocket(WILD, btid=0, **pub_kwargs)
        t = threading.Thread(
            target=lambda: [pub.publish(**it) for it in items], daemon=True
        )
        t.start()
        got = list(RemoteStream([pub.addr], max_items=8, timeoutms=8000))
        t.join(timeout=10)
        detach_all()
        pub.close()
        return got

    via_shm = _collect(shm=4)
    via_ndz = _collect(compress_level=6, compress_min_bytes=1)

    def _losses(msgs):
        batch = {
            "image": np.stack([m["image"] for m in msgs]),
            "xy": np.stack([m["xy"] for m in msgs]),
        }
        state = make_train_state(
            CubeRegressor(), batch["image"], optimizer=optax.sgd(0.01),
        )
        step = make_supervised_step(donate=False)
        out = []
        for _ in range(3):
            state, m = step(state, batch)
            out.append(float(m["loss"]))
        return out

    for a, b in zip(via_shm, via_ndz):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["xy"], b["xy"])
    assert _losses(via_shm) == _losses(via_ndz)
