"""Headless simulation engine: rendering + annotation consistency."""

import numpy as np

from blendjax.producer.animation import AnimationController
from blendjax.producer.sim import (
    CartpoleScene,
    CubeScene,
    FallingCubesScene,
    SimEngine,
    SupershapeScene,
)


def test_cube_scene_renders_cube_where_annotated():
    scene = CubeScene(shape=(120, 160), seed=3)
    scene.step(1)
    obs = scene.observation(1)
    img, xy = obs["image"], obs["xy"]
    assert img.shape == (120, 160, 4) and img.dtype == np.uint8
    assert xy.shape == (8, 2)
    # cube must actually be drawn (non-background pixels exist)
    nonbg = (img[..., :3] != 0).any(axis=-1)
    assert nonbg.sum() > 50
    # the annotated corner centroid must sit inside the drawn blob's bbox
    ys, xs = np.nonzero(nonbg)
    cx, cy = xy[:, 0].mean(), xy[:, 1].mean()
    assert xs.min() - 1 <= cx <= xs.max() + 1
    assert ys.min() - 1 <= cy <= ys.max() + 1


def test_cube_scene_deterministic_by_seed():
    a = CubeScene(shape=(60, 80), seed=7)
    b = CubeScene(shape=(60, 80), seed=7)
    a.step(1)
    b.step(1)
    np.testing.assert_array_equal(a.observation(1)["image"], b.observation(1)["image"])
    c = CubeScene(shape=(60, 80), seed=8)
    c.step(1)
    assert (c.observation(1)["image"] != a.observation(1)["image"]).any()


def test_falling_cubes_fall_and_settle_above_ground():
    scene = FallingCubesScene(shape=(60, 80), seed=0, num_cubes=4)
    z0 = scene.pos[:, 2].copy()
    for f in range(1, 120):
        scene.step(f)
    assert (scene.pos[:, 2] < z0).all()  # fell
    assert (scene.pos[:, 2] >= scene.half - 1e-9).all()  # never below ground
    obs = scene.observation(120)
    assert obs["image"].shape == (60, 80, 4)
    assert obs["xy"].shape == (4, 2)


def test_supershape_params_change_image():
    scene = SupershapeScene(shape=(64, 64), seed=0)
    scene.set_params([6, 1, 1, 1], shape_id=1)
    img1 = scene.observation(1)
    scene.set_params([3, 0.5, 1.7, 1.7], shape_id=2)
    img2 = scene.observation(2)
    assert img1["shape_id"] == 1 and img2["shape_id"] == 2
    assert (img1["image"] != img2["image"]).any()


def test_cartpole_physics_falls_without_control():
    scene = CartpoleScene(seed=1)
    scene.state = np.array([0.0, 0.0, 0.05, 0.0])  # slight tilt
    for f in range(1, 200):
        scene.step(f)
    assert abs(scene.state[2]) > 0.5  # pole fell over
    img = scene.render()
    assert img.shape == (240, 320, 4)
    assert (img[..., :3] != 0).any()


def test_cartpole_motor_moves_cart():
    scene = CartpoleScene(seed=1)
    scene.state[:] = 0.0
    scene.apply_motor(2.0)
    for f in range(1, 60):
        scene.step(f)
    assert scene.state[0] > 0.5  # cart moved right


def test_sim_engine_with_controller_streams_frames():
    scene = CubeScene(shape=(32, 32), seed=0)
    frames = []
    ctrl = AnimationController(SimEngine(scene))
    ctrl.post_frame.add(lambda f: frames.append(scene.observation(f)["frameid"]))
    ctrl.play(frame_range=(1, 5), num_episodes=2)
    assert frames == [1, 2, 3, 4, 5] * 2


def test_render_into_out_buffer_matches_copy():
    scene = CubeScene(shape=(60, 80), seed=5)
    scene.step(1)
    img = scene.render()
    batch = np.empty((3, 60, 80, 4), np.uint8)
    ret = scene.render(out=batch[1])
    assert ret.base is batch
    np.testing.assert_array_equal(batch[1], img)


def test_observation_into_matches_observation():
    a = CubeScene(shape=(60, 80), seed=9)
    b = CubeScene(shape=(60, 80), seed=9)
    a.step(1)
    b.step(1)
    obs = a.observation(7)
    buf = {
        "image": np.empty((2, 60, 80, 4), np.uint8),
        "xy": np.empty((2, 8, 2), np.float32),
        "frameid": np.empty((2,), np.int64),
    }
    b.observation_into(7, buf, 0)
    np.testing.assert_array_equal(buf["image"][0], obs["image"])
    np.testing.assert_array_equal(buf["xy"][0], obs["xy"])
    assert buf["frameid"][0] == 7


def test_native_and_python_rasterizers_agree(monkeypatch):
    """The one-call C++ frame renderer and the numpy fallback draw the
    same cube (up to rounding at triangle-edge pixels: <1% of covered
    pixels may differ)."""
    import blendjax._native.build as build

    native = CubeScene(shape=(120, 160), seed=11)
    native.step(1)
    if native.raster._native_frame is None:
        import pytest

        pytest.skip("native rasterizer unavailable")
    img_native = native.observation(1)["image"]

    monkeypatch.setenv("BLENDJAX_NO_NATIVE", "1")
    monkeypatch.setitem(build._CACHE, "render_frame", None)
    fallback = CubeScene(shape=(120, 160), seed=11)
    assert fallback.raster._native_frame is None
    fallback.step(1)
    img_py = fallback.observation(1)["image"]

    covered = ((img_native[..., :3] != 0).any(-1)
               | (img_py[..., :3] != 0).any(-1))
    differing = (img_native != img_py).any(-1)
    assert differing.sum() <= max(1, int(0.01 * covered.sum()))


def test_dirty_rect_rendering_bit_exact():
    """Re-rendering into the same buffer (dirty-rect clear path) matches
    a fresh full-clear render for every frame of a random sequence."""
    import numpy as np

    from blendjax.producer.sim import CubeScene

    fast = CubeScene(shape=(96, 128), seed=11)
    slow = CubeScene(shape=(96, 128), seed=11)
    buf = np.empty((96, 128, 4), np.uint8)
    for f in range(1, 12):
        fast.step(f)
        slow.step(f)
        out_fast = fast.render(out=buf)  # same buffer -> rect clears
        out_slow = slow.render()         # fresh internal buffer each call
        np.testing.assert_array_equal(out_fast, out_slow)
        assert fast.raster.last_drawn is not None


def test_dirty_rect_handles_empty_scene():
    import numpy as np

    from blendjax.producer.sim import Rasterizer, CubeScene

    scene = CubeScene(shape=(64, 64), seed=0)
    buf = np.empty((64, 64, 4), np.uint8)
    scene.step(1)
    scene.render(out=buf)
    r = scene.raster
    # no geometry: previous drawing must be restored to background
    empty = r.render(scene.camera, np.zeros((0, 3, 3)),
                     np.zeros((0, 4), np.uint8), out=buf)
    np.testing.assert_array_equal(empty, scene.background_image())
    assert r.last_drawn is None


def test_dirty_rect_does_not_false_match_reused_view_addresses():
    """Rendering into fresh views of a batch array must take the full
    clear each time: the previous-target comparison holds an array
    reference (id() of freed temporaries can collide)."""
    import numpy as np

    from blendjax.producer.sim import CubeScene

    scene = CubeScene(shape=(64, 64), seed=1)
    scene.step(1)
    frames = np.zeros((4, 64, 64, 4), np.uint8)  # garbage-prefilled slots
    for i in range(4):
        scene.render(out=frames[i])
    ref = CubeScene(shape=(64, 64), seed=1)
    ref.step(1)
    expected = ref.render()
    for i in range(4):
        np.testing.assert_array_equal(frames[i], expected)
