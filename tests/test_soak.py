"""Opt-in soak test: long-running tile stream, stable memory.

Run with ``BLENDJAX_SOAK=1 pytest tests/test_soak.py -q``. Guards
against slow leaks in the pipeline's per-batch bookkeeping (plans,
refs, chunk groups, recycled buffers) that short functional tests
can't see.
"""

import os

import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.skipif(
    os.environ.get("BLENDJAX_SOAK") != "1",
    reason="soak test (set BLENDJAX_SOAK=1)",
)

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen", "cube_producer.py"
)


def _rss_mb() -> float:
    # Current RSS (not getrusage's monotone high-water mark, which a
    # warm-up compile spike would pin above any later leak).
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024
    raise RuntimeError("VmRSS not found (non-Linux host?)")


def test_tile_stream_memory_stable_over_many_batches():
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, chunk=4,
            timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            for _ in range(50):  # settle allocators/compiles
                next(it)
            baseline = _rss_mb()
            for _ in range(1500):
                next(it)
            grown = _rss_mb() - baseline
    # current RSS; slack covers allocator noise, but a per-batch leak
    # shows clearly (1500 batches x even 100KB would be 150MB)
    assert grown < 100, f"RSS grew {grown:.0f}MB over 1500 batches"
