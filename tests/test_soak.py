"""Opt-in soak test: long-running tile stream, stable memory.

Run with ``BLENDJAX_SOAK=1 pytest tests/test_soak.py -q``. Guards
against slow leaks in the pipeline's per-batch bookkeeping (plans,
refs, chunk groups, recycled buffers) that short functional tests
can't see.
"""

import os

import numpy as np
import pytest

pytest.importorskip("jax")

pytestmark = pytest.mark.skipif(
    os.environ.get("BLENDJAX_SOAK") != "1",
    reason="soak test (set BLENDJAX_SOAK=1)",
)

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen", "cube_producer.py"
)


def _rss_mb() -> float:
    # Current RSS (not getrusage's monotone high-water mark, which a
    # warm-up compile spike would pin above any later leak).
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024
    raise RuntimeError("VmRSS not found (non-Linux host?)")


def test_tile_stream_memory_stable_over_many_batches():
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, chunk=4,
            timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            for _ in range(50):  # settle allocators/compiles
                next(it)
            baseline = _rss_mb()
            for _ in range(1500):
                next(it)
            grown = _rss_mb() - baseline
    # current RSS; slack covers allocator noise, but a per-batch leak
    # shows clearly (1500 batches x even 100KB would be 150MB)
    assert grown < 100, f"RSS grew {grown:.0f}MB over 1500 batches"


def test_respawn_under_load():
    """Kill producers repeatedly mid-stream: with respawn=True the
    launcher brings them back and the pipeline keeps yielding batches
    (VERDICT r2 item 7: respawn-under-load was never soaked)."""
    import os as _os
    import signal

    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA"],
        seed=0,
        respawn=True,
        instance_args=[["--shape", "64", "64", "--batch", "4"]] * 2,
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4, timeoutms=30_000,
            launcher=launcher,
        ) as pipe:
            it = iter(pipe)
            got = 0
            for round_ in range(6):
                for _ in range(25):
                    next(it)
                    got += 1
                # SIGKILL one producer (alternating); poll() respawns it
                victim = launcher.processes[round_ % 2]
                _os.kill(victim.pid, signal.SIGKILL)
                victim.wait()
                launcher.poll()  # respawn now (don't wait for a timeout)
            for _ in range(25):
                next(it)
                got += 1
    assert got == 175


def test_sustained_hwm_backpressure():
    """A slow consumer against fast producers for thousands of messages:
    HWM blocks the producers (bounded memory both sides), nothing is
    lost on the live socket, and the stream stays ordered per producer
    (VERDICT r2 item 7: sustained-backpressure was never soaked)."""
    import time

    from blendjax.data.stream import RemoteStream
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
        # ~1.2MB raw frames so HWM bites through kernel buffers
        instance_args=[["--shape", "480", "640", "--batch", "8",
                       "--encoding", "raw"]],
    ) as launcher:
        baseline = None
        last_frame = -1
        n = 0
        for msg in RemoteStream(
            launcher.addresses["DATA"], timeoutms=30_000, max_items=400,
        ):
            # slow consumer: ~5x slower than the producer renders
            time.sleep(0.02)
            fid = int(np.ravel(msg["frameid"])[-1])
            assert fid > last_frame  # per-producer FIFO, no reordering
            last_frame = fid
            n += 1
            if n == 50:
                baseline = _rss_mb()
        grown = _rss_mb() - (baseline or 0.0)
    assert n == 400
    # bounded queues: a slow consumer must not accumulate frames in RSS
    assert grown < 200, f"RSS grew {grown:.0f}MB under backpressure"


def test_long_recording_growth_and_replay(tmp_path):
    """Hours-style .bjr growth in miniature: record thousands of tile
    messages, verify linear file growth, an intact footer index, and a
    bit-exact replay of a sampled subset (VERDICT r2 item 7)."""
    from blendjax.data import FileReader, StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    prefix = str(tmp_path / "soak")
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=0,
        instance_args=[["--shape", "64", "64", "--batch", "8",
                       "--encoding", "tile", "--tile", "16"]],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, timeoutms=30_000,
            record_path_prefix=prefix,
        ) as pipe:
            it = iter(pipe)
            sizes = []
            for i in range(2000):
                next(it)
                if i % 500 == 499:
                    path = f"{prefix}_00.bjr"
                    sizes.append(
                        os.path.getsize(path) if os.path.exists(path) else 0
                    )
    path = f"{prefix}_00.bjr"
    reader = FileReader(path)
    assert len(reader) >= 2000
    # linear growth: each 500-batch window appends a similar byte count
    deltas = [b - a for a, b in zip(sizes, sizes[1:])]
    assert all(d > 0 for d in deltas)
    assert max(deltas) < 3 * min(deltas), f"nonlinear growth {deltas}"
    # sampled random access across the whole file decodes
    for idx in (0, len(reader) // 2, len(reader) - 1):
        msg = reader[idx]
        assert "image__tileidx" in msg or "image" in msg
    reader.close()
