"""threadguard (blendjax.testing.threadguard) tests: affinity and
lock-discipline violations raise at the access site, sanctioned paths
stay silent, and the disabled production indirection
(blendjax.utils.tg) is a true zero-overhead identity."""

import os
import subprocess
import sys
import threading

import pytest

from blendjax.testing.threadguard import (
    LockDisciplineError,
    ThreadAffinityError,
    ThreadGuardError,
    guard,
    unguard,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Box:
    def __init__(self):
        self.value = 0

    def bump(self):
        self.value += 1
        return self.value


def run_in_thread(fn):
    """Run fn on a fresh thread; return its result or raise its error."""
    out: dict = {}

    def wrapper():
        try:
            out["result"] = fn()
        except BaseException as e:  # re-raised on the caller's thread
            out["error"] = e

    t = threading.Thread(target=wrapper)
    t.start()
    t.join(5.0)
    assert not t.is_alive()
    if "error" in out:
        raise out["error"]
    return out.get("result")


# -- affinity ----------------------------------------------------------------


def test_creator_affinity_allows_creator_and_rejects_others():
    g = guard(Box(), name="box", affinity="creator")
    assert g.bump() == 1  # creating thread: fine
    with pytest.raises(ThreadAffinityError) as e:
        run_in_thread(g.bump)
    assert "box.bump" in str(e.value)
    assert threading.current_thread().name in str(e.value)


def test_first_use_affinity_binds_to_the_first_toucher():
    g = guard(Box(), name="box", affinity="first-use")
    assert run_in_thread(lambda: g.bump()) == 1  # the binder
    with pytest.raises(ThreadAffinityError):
        g.bump()  # main thread is now the intruder


def test_affinity_error_is_a_threadguard_and_assertion_error():
    g = guard(Box(), affinity="creator")
    try:
        run_in_thread(g.bump)
    except ThreadGuardError as e:
        assert isinstance(e, AssertionError)
    else:
        pytest.fail("expected ThreadAffinityError")


# -- lock discipline ---------------------------------------------------------


def test_lock_mode_requires_holding_an_rlock():
    lock = threading.RLock()
    g = guard(Box(), name="box", lock=lock)
    with pytest.raises(LockDisciplineError) as e:
        g.bump()
    assert "box.bump" in str(e.value)
    with lock:
        assert g.bump() == 1


def test_rlock_ownership_is_exact_not_merely_locked():
    """Another thread holding the RLock must NOT satisfy the check."""
    lock = threading.RLock()
    g = guard(Box(), lock=lock)
    acquired = threading.Event()
    release = threading.Event()

    def holder():
        with lock:
            acquired.set()
            release.wait(5.0)

    t = threading.Thread(target=holder)
    t.start()
    assert acquired.wait(5.0)
    try:
        with pytest.raises(LockDisciplineError):
            g.bump()
    finally:
        release.set()
        t.join(5.0)


def test_plain_lock_degrades_to_locked_check():
    lock = threading.Lock()
    g = guard(Box(), lock=lock)
    with pytest.raises(LockDisciplineError):
        g.bump()
    with lock:
        assert g.bump() == 1


def test_container_dunders_are_checked():
    lock = threading.RLock()
    g = guard({}, name="table", lock=lock)
    with pytest.raises(LockDisciplineError):
        g["k"] = 1
    with lock:
        g["k"] = 1
        assert g["k"] == 1
        assert "k" in g and len(g) == 1 and list(g) == ["k"]
    with pytest.raises(LockDisciplineError):
        len(g)


def test_exempt_attributes_skip_the_checks():
    lock = threading.RLock()
    box = Box()
    box.lock = lock
    g = guard(box, lock=lock, exempt=("lock",))
    assert g.lock is lock  # fetchable BEFORE holding it
    with pytest.raises(LockDisciplineError):
        g.bump()
    with g.lock:
        assert g.bump() == 1


# -- mechanics ----------------------------------------------------------------


def test_guard_is_idempotent_and_unguard_returns_the_raw_object():
    box = Box()
    g = guard(box, affinity="creator")
    assert guard(g, affinity="creator") is g
    assert unguard(g) is box
    assert unguard(box) is box


def test_guard_requires_a_discipline():
    with pytest.raises(ValueError):
        guard(Box())
    with pytest.raises(ValueError):
        guard(Box(), affinity="psychic")


# -- the production indirection (blendjax.utils.tg) ---------------------------


def _tg_probe(env_value):
    """Import blendjax.utils.tg in a fresh interpreter and report
    whether guard() is the identity."""
    env = {k: v for k, v in os.environ.items() if k != "BLENDJAX_THREADGUARD"}
    if env_value is not None:
        env["BLENDJAX_THREADGUARD"] = env_value
    env["PYTHONPATH"] = REPO_ROOT
    code = (
        "from blendjax.utils.tg import guard\n"
        "import threading\n"
        "o = object()\n"
        "print(guard(o, name='x', lock=threading.Lock()) is o)\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    assert r.returncode == 0, r.stderr
    return r.stdout.strip()


def test_tg_guard_is_identity_when_disabled():
    """The zero-overhead contract: no proxy, no per-access cost, no
    threadguard import on any hot path unless the env opts in."""
    assert _tg_probe(None) == "True"
    assert _tg_probe("0") == "True"


def test_tg_guard_wraps_when_enabled():
    assert _tg_probe("1") == "False"


def test_enabled_env_turns_metrics_lock_discipline_on():
    """End to end through the wiring: an unlocked counter-table write
    inside a guarded registry raises; the public API stays fine."""
    env = {**os.environ, "BLENDJAX_THREADGUARD": "1",
           "PYTHONPATH": REPO_ROOT}
    code = (
        "from blendjax.utils.metrics import Metrics\n"
        "from blendjax.testing.threadguard import LockDisciplineError\n"
        "m = Metrics()\n"
        "m.count('ok')                  # locked path: fine\n"
        "assert m.counter_value('ok') == 1\n"
        "try:\n"
        "    m.counters['raw'] = 1      # unlocked mutation: must raise\n"
        "except LockDisciplineError:\n"
        "    print('raised')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True, env=env
    )
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "raised"
