"""Tile-delta stream encoding (blendjax.ops.tiles): exact reconstruction,
native/numpy agreement, packing buckets, and the end-to-end sparse
streaming path through StreamDataPipeline on the virtual CPU mesh."""

import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from blendjax.ops.tiles import (  # noqa: E402
    TILE,
    TileDeltaEncoder,
    decode_tile_delta,
    pack_batch,
    tile_grid,
    tile_ref,
)

PRODUCER = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen", "cube_producer.py"
)
FALLING = os.path.join(
    os.path.dirname(__file__), "..", "examples", "datagen",
    "falling_cubes_producer.py",
)


def _frames(n=6, shape=(64, 96), seed=0):
    """Reference + frames that sparsely edit random tiles of it."""
    rng = np.random.default_rng(seed)
    h, w = shape
    ref = rng.integers(0, 255, (h, w, 4), np.uint8)
    frames = []
    for _ in range(n):
        img = ref.copy()
        for _ in range(rng.integers(0, 5)):
            y, x = rng.integers(0, h - 8), rng.integers(0, w - 8)
            img[y : y + 8, x : x + 8] = rng.integers(0, 255, (8, 8, 4))
        frames.append(img)
    return ref, frames


@pytest.mark.parametrize("native", [True, False])
def test_roundtrip_exact(native):
    if native and os.environ.get("BLENDJAX_NO_NATIVE") == "1":
        pytest.skip("native disabled")
    ref, frames = _frames()
    enc = TileDeltaEncoder(ref, tile=16)
    if not native:
        enc._native = None
    elif enc._native is None:
        pytest.skip("no toolchain")
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    out = np.asarray(
        decode_tile_delta(tile_ref(ref, 16), idx, tiles, ref.shape)
    )
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(out[i], f)


def test_native_matches_numpy():
    ref, frames = _frames(seed=3)
    enc_n = TileDeltaEncoder(ref, tile=16)
    if enc_n._native is None:
        pytest.skip("no toolchain")
    enc_p = TileDeltaEncoder(ref, tile=16)
    enc_p._native = None
    for f in frames:
        i1, t1 = enc_n.encode(f)
        i1, t1 = i1.copy(), t1.copy()
        i2, t2 = enc_p.encode(f)
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(t1, t2)


def test_identical_frame_encodes_empty_and_full_change_encodes_all():
    ref, _ = _frames()
    enc = TileDeltaEncoder(ref, tile=16)
    idx, _tiles = enc.encode(ref.copy())
    assert len(idx) == 0
    inv = (255 - ref).astype(np.uint8)
    idx, _tiles = enc.encode(inv)
    assert len(idx) == enc.num_tiles


def test_pack_batch_buckets_and_sentinel():
    ref, frames = _frames()
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles, bucket=16)
    kmax = max(len(i) for i, _ in deltas)
    assert idx.shape[1] == max(-(-kmax // 16) * 16, 16)
    assert idx.shape[1] <= enc.num_tiles
    for i, (fi, _) in enumerate(deltas):
        assert (idx[i, len(fi):] == enc.num_tiles).all()  # sentinel padding
    assert tiles.shape == (len(frames), idx.shape[1], 16, 16, 4)


def test_decode_rgb_tiles_reconstructs_alpha_from_ref():
    """Channel-sliced tiles (alpha-static streams) still decode exactly."""
    ref, frames = _frames(seed=7)
    # Make alpha static: copy ref's alpha into every frame.
    frames = [np.dstack([f[..., :3], ref[..., 3]]) for f in frames]
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    out = np.asarray(
        decode_tile_delta(
            tile_ref(ref, 16), idx, np.ascontiguousarray(tiles[..., :3]),
            ref.shape,
        )
    )
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(out[i], f)


def test_tile_grid_requires_divisibility():
    assert tile_grid((64, 96, 4), 16) == (4, 6)
    with pytest.raises(ValueError):
        tile_grid((65, 96, 4), 16)


def test_decode_sharded_on_mesh():
    """Batch-sharded idx/tiles + replicated ref decode shard-locally."""
    ref, frames = _frames(n=8)
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    bsh = NamedSharding(mesh, P("data"))
    rsh = NamedSharding(mesh, P())
    out = jax.jit(decode_tile_delta, static_argnames=("shape",))(
        jax.device_put(tile_ref(ref, 16), rsh),
        jax.device_put(idx, bsh),
        jax.device_put(tiles, bsh),
        shape=ref.shape,
    )
    assert out.shape == (8, *ref.shape)
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(np.asarray(out[i]), f)


def test_stream_pipeline_tile_encoding_end_to_end():
    """One producer with --encoding tile -> bit-exact full frames on
    device, verified against a local re-render of the same seeded scene
    (single producer + PUSH FIFO => frames arrive in order)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    sharding = NamedSharding(mesh, P("data"))
    seed = 5
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=8,
            sharding=sharding,
            timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            batches = [next(it) for _ in range(3)]

    # Re-render the same deterministic stream locally (launcher hands the
    # instance seed+0; frames play 1, 2, 3, ...).
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 8 * len(batches) + 1):
        scene.step(f)
        local[f] = scene.render().copy()

    for b in batches:
        assert b["image"].shape == (8, 64, 64, 4)
        assert b["image"].dtype == np.uint8
        assert b["image"].sharding.is_equivalent_to(sharding, 4)
        img = np.asarray(b["image"])
        fids = np.asarray(b["frameid"])
        for i, f in enumerate(fids):
            np.testing.assert_array_equal(img[i], local[int(f)])


def test_falling_cubes_tile_stream():
    """The reusable TileBatchPublisher path on a second scene/producer."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=FALLING,
        num_instances=1,
        named_sockets=["DATA"],
        seed=2,
        instance_args=[
            ["--shape", "64", "64", "--encoding", "tile", "--batch", "4",
             "--num-cubes", "3"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4, timeoutms=30_000
        ) as pipe:
            it = iter(pipe)
            batches = [next(it) for _ in range(2)]
    for b in batches:
        assert b["image"].shape == (4, 64, 64, 4)
        assert b["xy"].shape == (4, 3, 2)
        img = np.asarray(b["image"])
        assert img.any()  # cubes rendered, not just background


def test_tile_publisher_direct_pack_overflow_and_flush():
    """The direct-pack fast path (pinned capacity): frames encode
    straight into the batch arrays, a frame exceeding the capacity grows
    it mid-batch (migrating packed rows), a partial flush ships the
    filled prefix — all bit-exact on host-side decode."""
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILESHAPE_SUFFIX,
        decode_tile_delta_np,
        pop_tile_payload,
        expand_palette_tiles_np,
    )
    from blendjax.producer.tile_publisher import TileBatchPublisher

    class Capture:
        def __init__(self):
            self.msgs = []

        def publish(self, **kw):
            self.msgs.append(kw)

    rng = np.random.default_rng(6)
    ref = rng.integers(0, 255, (64, 64, 4), np.uint8)
    cap = Capture()
    pub = TileBatchPublisher(cap, ref, batch_size=3, tile=16,
                             alpha_slice=False, capacity=2)
    frames = []
    # frame edits: 1 tile, then 5 tiles (overflow: 2 -> 32... clamped to
    # num_tiles=16), then 2, then 1 (partial batch -> flush)
    for ntiles in (1, 5, 2, 1):
        img = ref.copy()
        for j in range(ntiles):
            ty, tx = divmod(j, 4)
            img[ty * 16: ty * 16 + 4, tx * 16: tx * 16 + 4] = rng.integers(
                0, 255, (4, 4, 4), np.uint8
            )
        frames.append(img)
        pub.add(img, frameid=np.int64(len(frames)))
    pub.flush()
    assert len(cap.msgs) == 2  # one full batch of 3 + flushed tail of 1
    for msg, batch in zip(cap.msgs, (frames[:3], frames[3:])):
        msg = dict(msg)
        idx = msg.pop("image" + TILEIDX_SUFFIX)
        geom = msg.pop("image" + TILESHAPE_SUFFIX)
        tiles = pop_tile_payload(msg, "image", geom, expand_palette_tiles_np)
        out = decode_tile_delta_np(ref, idx, tiles, tile=16)
        assert len(out) == len(batch)
        for got, want in zip(out, batch):
            np.testing.assert_array_equal(got, want)
    # capacity grew past the overflow and stayed 32-aligned (clamped to
    # the 16-tile grid)
    assert pub._capacity == 16


def test_tile_publisher_fused_engages_for_rgb_default_config():
    """3-channel streams have no alpha plane, so the default
    alpha_slice=True is inert and must not disable the fused path; the
    shipped palette is zero-padded past the used entries."""
    from blendjax.ops.tiles import PALETTE_SUFFIX
    from blendjax.producer.tile_publisher import TileBatchPublisher

    class Capture:
        def __init__(self):
            self.msgs = []

        def publish(self, **kw):
            self.msgs.append(kw)

    ref = np.zeros((32, 32, 3), np.uint8)
    cap = Capture()
    pub = TileBatchPublisher(cap, ref, batch_size=2, tile=16, capacity=4)
    assert pub._fused_ok
    img = ref.copy()
    img[0:8, 0:8] = (1, 2, 3)
    pub.add(img)
    pub.add(img)
    (msg,) = cap.msgs
    from blendjax.ops.tiles import TILEPAL2_SUFFIX

    pal = msg["image" + PALETTE_SUFFIX]
    # <=4 colors per frame => 2-bit indices ship (the densest form)
    packed = msg["image" + TILEPAL2_SUFFIX]
    # per-frame palettes: one (cap, C) table per batch row
    assert pal.ndim == 3 and pal.shape[0] == 2
    for row_pal, row_packed in zip(pal, packed):
        # highest palette index any pixel references bounds the used
        # entries; everything past it must be zero (wire contract —
        # stale table rows must never ship)
        hi = int(max(
            (row_packed >> 6).max(), ((row_packed >> 4) & 3).max(),
            ((row_packed >> 2) & 3).max(), (row_packed & 3).max(),
        ))
        assert hi >= 1  # bg + the edited square's color
        assert (row_pal[hi + 1:] == 0).all()


def test_tile_publisher_raw_direct_pack_path():
    """palette=False: the direct-pack raw path (no fused palettizer)
    ships copied raw tiles, bit-exact, with reused batch arrays."""
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILES_SUFFIX,
        decode_tile_delta_np,
    )
    from blendjax.producer.tile_publisher import TileBatchPublisher

    class Capture:
        def __init__(self):
            self.msgs = []

        def publish(self, **kw):
            self.msgs.append(kw)

    rng = np.random.default_rng(14)
    ref = rng.integers(0, 255, (64, 64, 4), np.uint8)
    cap = Capture()
    pub = TileBatchPublisher(cap, ref, batch_size=2, tile=16,
                             alpha_slice=False, palette=False, capacity=4)
    assert not pub._fused_ok
    frames = []
    for n in range(4):
        img = ref.copy()
        img[0:8, 0:8] = rng.integers(0, 255, (8, 8, 4), np.uint8)
        frames.append(img)
        pub.add(img)
    assert len(cap.msgs) == 2
    # reused batch arrays must not alias the shipped tiles
    assert cap.msgs[0]["image" + TILES_SUFFIX].base is not pub._batch_tiles
    for msg, batch in zip(cap.msgs, (frames[:2], frames[2:])):
        out = decode_tile_delta_np(
            ref, msg["image" + TILEIDX_SUFFIX],
            msg["image" + TILES_SUFFIX], tile=16,
        )
        for got, want in zip(out, batch):
            np.testing.assert_array_equal(got, want)


def test_tile_publisher_fused_palette_overflow_falls_back():
    """A frame pushing the persistent stream palette past 256 colors
    latches the fused path off mid-batch; already-packed rows
    reconstruct from their indices (lossless) and the batch ships raw
    tiles — everything still decodes bit-exact."""
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILESHAPE_SUFFIX,
        decode_tile_delta_np,
        expand_palette_tiles_np,
        pop_tile_payload,
    )
    from blendjax.producer.tile_publisher import TileBatchPublisher

    class Capture:
        def __init__(self):
            self.msgs = []

        def publish(self, **kw):
            self.msgs.append(kw)

    rng = np.random.default_rng(15)
    ref = np.zeros((64, 64, 4), np.uint8)
    cap = Capture()
    pub = TileBatchPublisher(cap, ref, batch_size=2, tile=16,
                             alpha_slice=False, capacity=8)
    assert pub._fused_ok
    flat = ref.copy()
    flat[0:16, 0:16] = (10, 20, 30, 255)  # few colors: fused packs it
    rich = ref.copy()
    rich[0:32, 0:32] = rng.integers(0, 255, (32, 32, 4), np.uint8)  # ~1k
    pub.add(flat)
    pub.add(rich)  # overflow mid-batch -> raw fallback for THIS batch
    assert pub._fused_ok  # one overflow does not latch fused off
    # one miss from the fused overflow + one from the publish-time
    # two-pass palettize also failing on the color-rich batch
    assert pub._palette_misses == 2
    pub.add(flat)
    pub.add(flat)  # next batch: fused again (per-batch table reset)
    assert len(cap.msgs) == 2
    for msg, batch in zip(cap.msgs, ((flat, rich), (flat, flat))):
        msg = dict(msg)
        idx = msg.pop("image" + TILEIDX_SUFFIX)
        geom = msg.pop("image" + TILESHAPE_SUFFIX)
        tiles = pop_tile_payload(
            msg, "image", geom, expand_palette_tiles_np
        )
        out = decode_tile_delta_np(ref, idx, tiles, tile=16)
        for got, want in zip(out, batch):
            np.testing.assert_array_equal(got, want)
    # batch 1 shipped raw tiles (overflow), batch 2 palette again
    from blendjax.ops.tiles import TILEPAL2_SUFFIX, TILES_SUFFIX

    assert "image" + TILES_SUFFIX in cap.msgs[0]
    # <=4 colors => the 2-bit palette form ships
    assert "image" + TILEPAL2_SUFFIX in cap.msgs[1]
    assert pub._palette_misses == 0  # success resets the miss latch


def test_tile_producer_partial_tail_flush():
    """--frames not a multiple of --batch: trailing frames still arrive
    (ragged prebatched passthrough)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=9,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--frames", "12",
             "--encoding", "tile", "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, timeoutms=30_000,
            max_items=2,
        ) as pipe:
            batches = list(pipe)
    sizes = sorted(b["image"].shape[0] for b in batches)
    assert sizes == [4, 8]
    got = sorted(
        int(f) for b in batches for f in np.asarray(b["frameid"])
    )
    assert got == list(range(1, 13))


def test_pack_unpack_fields_dtypes_roundtrip():
    """Packed single-transfer form reconstructs every supported dtype
    exactly (float64 value-cast to f32 like device_put canonicalization,
    bools as bytes, signed bytes bitcast)."""
    from blendjax.ops.tiles import pack_fields, unpack_fields

    fields = {
        "u8": np.random.randint(0, 255, (4, 3, 3), np.uint8),
        "i8": np.random.randint(-128, 127, (5,), np.int8),
        "f32": np.random.randn(2, 7).astype(np.float32),
        "f64": np.array([1.5, -2.25, 1e6]),
        "i64": np.array([1, -7, 2**31 - 1], np.int64),
        "bool": np.array([True, False, True]),
        "i32": np.arange(6, dtype=np.int32).reshape(2, 3),
    }
    buf, spec = pack_fields(fields)
    assert buf.dtype == np.uint8 and buf.ndim == 1
    out = jax.jit(unpack_fields, static_argnames=("spec",))(buf, spec)
    np.testing.assert_array_equal(np.asarray(out["u8"]), fields["u8"])
    np.testing.assert_array_equal(np.asarray(out["i8"]), fields["i8"])
    np.testing.assert_array_equal(np.asarray(out["f32"]), fields["f32"])
    np.testing.assert_array_equal(
        np.asarray(out["f64"]), fields["f64"].astype(np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(out["i64"]), fields["i64"].astype(np.int32)
    )
    np.testing.assert_array_equal(np.asarray(out["bool"]), fields["bool"])
    np.testing.assert_array_equal(np.asarray(out["i32"]), fields["i32"])


def test_pack_fields_overflowing_int64_raises():
    """Integer narrowing is range-checked: a time_ns-style sidecar value
    that doesn't fit 32 bits raises instead of silently wrapping."""
    from blendjax.ops.tiles import pack_fields

    with pytest.raises(ValueError, match="do not fit"):
        pack_fields({"t_ns": np.array([1_722_000_000_000_000_000], np.int64)})
    with pytest.raises(ValueError, match="do not fit"):
        pack_fields({"u": np.array([2**33], np.uint64)})


def test_pack_fields_keeps_64bit_under_x64():
    """With jax_enable_x64, device_put would keep 64 bits — the packed
    path must match the raw-frame path bit for bit, so no narrowing."""
    from blendjax.ops.tiles import pack_fields, unpack_fields

    big = np.array([2**40, -(2**40)], np.int64)
    jax.config.update("jax_enable_x64", True)
    try:
        buf, spec = pack_fields({"big": big})
        out = jax.jit(unpack_fields, static_argnames=("spec",))(buf, spec)
        np.testing.assert_array_equal(np.asarray(out["big"]), big)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_pack_batch_padding_is_zeroed():
    ref, frames = _frames()
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles, bucket=16)
    for i, (fi, _) in enumerate(deltas):
        assert (tiles[i, len(fi):] == 0).all()


def test_record_then_replay_tile_stream_bit_exact(tmp_path):
    """A recorded tile-delta stream replays through the full device
    pipeline with no producers running, bit-exact vs a local re-render
    (SURVEY.md §5 checkpoint/resume: record/replay is the stream's
    checkpoint analog — it must compose with the sparse encoding)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    prefix = str(tmp_path / "rec")
    seed = 3
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--frames", "16",
             "--encoding", "tile", "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, timeoutms=30_000,
            max_items=2, record_path_prefix=prefix,
            # a dead producer then raises with its exit code instead of
            # an opaque 30s timeout (this test flaked under heavy
            # machine load; make the failure mode diagnosable)
            launcher=launcher,
        ) as pipe:
            live = list(pipe)
    assert len(live) == 2

    replayed = list(
        StreamDataPipeline.from_recording(f"{prefix}_00.bjr", batch_size=8)
    )
    assert len(replayed) == 2

    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 17):
        scene.step(f)
        local[f] = scene.render().copy()
    for b in replayed:
        img = np.asarray(b["image"])
        for i, f in enumerate(np.asarray(b["frameid"])):
            np.testing.assert_array_equal(img[i], local[int(f)])


def test_encode_hint_matches_full_scan():
    """A hint rect covering everything that differs from the ref yields
    the identical delta as the full scan (native and numpy paths)."""
    from blendjax.producer.sim import CubeScene

    scene = CubeScene(shape=(64, 96), seed=4)
    ref = scene.background_image()
    for native in (True, False):
        enc = TileDeltaEncoder(ref, tile=16)
        if not native:
            enc._native = None
        elif enc._native is None:
            continue
        for f in range(1, 6):
            scene.step(f)
            img = scene.render()
            full = tuple(a.copy() for a in enc.encode(img))
            hinted = enc.encode(img, hint=scene.raster.last_drawn)
            np.testing.assert_array_equal(hinted[0], full[0])
            np.testing.assert_array_equal(hinted[1], full[1])
        # degenerate hint: empty rect -> empty delta
        i, t = enc.encode(ref.copy(), hint=(5, 5, 0, 0))
        assert len(i) == 0 and len(t) == 0


def test_rect_tiles_roundtrip_all_decoders():
    """Rectangular (16, 32) tiles — the geometry whose tile row spans
    exactly 128 lanes at C=4, unlocking the direct-spatial Pallas decode
    — encode identically on the native and numpy paths and reconstruct
    bit-exactly through the XLA scatter, the spatial kernel (interpret
    mode off-TPU), and the host-side numpy decoder."""
    from blendjax.ops.tiles import decode_tile_delta_np

    ref, frames = _frames(n=5, shape=(64, 96), seed=23)
    enc = TileDeltaEncoder(ref, tile=(16, 32))
    enc_np = TileDeltaEncoder(ref, tile=(16, 32))
    enc_np._native = None
    assert enc.grid == (4, 3) and enc.num_tiles == 12
    deltas = []
    for f in frames:
        fi, ft = (a.copy() for a in enc.encode(f))
        if enc._native is not None:
            ni, nt = enc_np.encode(f)
            np.testing.assert_array_equal(fi, ni)
            np.testing.assert_array_equal(ft, nt)
        deltas.append((fi, ft))
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    assert tiles.shape[2:] == (16, 32, 4)
    rt = tile_ref(ref, (16, 32))
    xla = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=False)
    )
    spatial = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=True)
    )
    host = decode_tile_delta_np(ref, idx, tiles)
    np.testing.assert_array_equal(xla, spatial)
    np.testing.assert_array_equal(xla, host)
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(spatial[i], f)


def test_spatial_decode_empty_capacity_and_identical_frames():
    """Spatial-kernel edge cases: K=0 capacity returns pure reference
    frames; all-sentinel rows (identical frames at nonzero capacity)
    also reconstruct as the reference."""
    rng = np.random.default_rng(29)
    ref = rng.integers(0, 255, (32, 64, 4), np.uint8)
    rt = tile_ref(ref, (16, 32))
    n = 2 * 2
    b = 3
    idx0 = np.empty((b, 0), np.int32)
    tiles0 = np.empty((b, 0, 16, 32, 4), np.uint8)
    out0 = np.asarray(
        decode_tile_delta(rt, idx0, tiles0, ref.shape, use_pallas=True)
    )
    idx_s = np.full((b, 2), n, np.int32)  # all sentinels
    tiles_s = np.zeros((b, 2, 16, 32, 4), np.uint8)
    out_s = np.asarray(
        decode_tile_delta(rt, idx_s, tiles_s, ref.shape, use_pallas=True)
    )
    for bi in range(b):
        np.testing.assert_array_equal(out0[bi], ref)
        np.testing.assert_array_equal(out_s[bi], ref)


def test_sharded_spatial_decode_on_mesh():
    """The direct-spatial kernel survives scale-out the same way the
    slot scatter does: shard_map over the mesh's data axis, bit-exact
    against the XLA path on the virtual 8-device mesh."""
    from blendjax.parallel import create_mesh

    mesh = create_mesh({"data": -1})
    ref, frames = _frames(n=8, shape=(64, 64), seed=31)
    enc = TileDeltaEncoder(ref, tile=(16, 32))
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    rt = tile_ref(ref, (16, 32))
    sharded = np.asarray(
        decode_tile_delta(
            rt, idx, tiles, ref.shape, use_pallas=True, mesh=mesh
        )
    )
    xla = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=False)
    )
    np.testing.assert_array_equal(sharded, xla)
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(sharded[i], f)


def test_channel_sliced_tiles_take_kernel_paths():
    """Alpha-sliced (RGB-of-RGBA) streams stay kernel-eligible: the
    decode restores the missing channel from the reference on device
    and runs the spatial (rect) or slot (square) kernel — bit-exact vs
    the XLA path that handles Ct < C natively."""
    for tile in ((16, 32), 16):
        ref, frames = _frames(n=4, shape=(64, 64), seed=37)
        # make alpha static so slicing is valid: frames share ref alpha
        for f in frames:
            f[..., 3] = ref[..., 3]
        enc = TileDeltaEncoder(ref, tile=tile)
        deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
        idx, tiles = pack_batch(deltas, enc.num_tiles)
        rgb = np.ascontiguousarray(tiles[..., :3])
        rt = tile_ref(ref, tile)
        xla = np.asarray(
            decode_tile_delta(rt, idx, rgb, ref.shape, use_pallas=False)
        )
        kern = np.asarray(
            decode_tile_delta(rt, idx, rgb, ref.shape, use_pallas=True)
        )
        np.testing.assert_array_equal(xla, kern)
        for i, f in enumerate(frames):
            np.testing.assert_array_equal(kern[i], f)
    # forcing the kernel on an ineligible geometry fails loudly instead
    # of silently measuring the XLA path
    ref8 = np.zeros((64, 64, 4), np.uint8)
    with pytest.raises(ValueError, match="kernel-eligible"):
        decode_tile_delta(
            tile_ref(ref8, 8), np.zeros((1, 1), np.int32),
            np.zeros((1, 1, 8, 8, 4), np.uint8), ref8.shape,
            use_pallas=True,
        )


def test_tileshape_wire_geom_roundtrip():
    """Wire-geometry helpers: the square v1 4-element form and the
    rectangular 5-element form round-trip through geom_tile."""
    from blendjax.ops.tiles import geom_tile, tile_hw, tileshape_wire

    assert tileshape_wire(64, 96, 4, 16) == [64, 96, 4, 16]
    assert tileshape_wire(64, 96, 4, (16, 16)) == [64, 96, 4, 16]
    assert tileshape_wire(64, 96, 4, (16, 32)) == [64, 96, 4, 16, 32]
    assert geom_tile((64, 96, 4, 16)) == (16, 16)
    assert geom_tile((64, 96, 4, 16, 32)) == (16, 32)
    assert tile_hw(16) == (16, 16)
    assert tile_hw((8, 32)) == (8, 32)
    with pytest.raises(ValueError):
        tile_hw((1, 2, 3))


def test_rect_tile_publisher_end_to_end_wire():
    """TileBatchPublisher with rectangular tiles ships the 5-element
    __tileshape form (fused per-frame-palette path included) and the
    shared consumer helpers reconstruct bit-exact frames."""
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILESHAPE_SUFFIX,
        decode_tile_delta_np,
        expand_palette_tiles_np,
        pop_tile_payload,
    )
    from blendjax.producer.sim import CubeScene
    from blendjax.producer.tile_publisher import TileBatchPublisher

    class Capture:
        def __init__(self):
            self.msgs = []

        def publish(self, **kw):
            self.msgs.append(kw)

    scene = CubeScene(shape=(64, 96), seed=7)
    ref = scene.background_image()
    cap = Capture()
    pub = TileBatchPublisher(cap, ref, batch_size=4, tile=(16, 32),
                             alpha_slice=False, capacity=6)
    frames = []
    for f in range(1, 5):
        scene.step(f)
        img = scene.render()
        frames.append(img.copy())
        pub.add(img, frameid=np.int64(f))
    assert len(cap.msgs) == 1
    msg = dict(cap.msgs[0])
    geom = tuple(int(v) for v in msg.pop("image" + TILESHAPE_SUFFIX))
    assert geom == (64, 96, 4, 16, 32)
    idx = msg.pop("image" + TILEIDX_SUFFIX)
    tiles = pop_tile_payload(msg, "image", geom, expand_palette_tiles_np)
    assert tiles.shape[2:] == (16, 32, 4)
    out = decode_tile_delta_np(ref, idx, tiles)
    for got, want in zip(out, frames):
        np.testing.assert_array_equal(got, want)


def test_pallas_scatter_decode_matches_xla_scatter():
    """The Pallas scalar-prefetch scatter kernel (interpret mode off-TPU)
    reconstructs identically to the XLA .at[].set path."""
    ref, frames = _frames(n=4, shape=(64, 64), seed=13)
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    rt = tile_ref(ref, 16)
    a = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=False)
    )
    b = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=True)
    )
    np.testing.assert_array_equal(a, b)
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(b[i], f)


def test_sharded_pallas_scatter_decode_on_mesh():
    """The shard_map-partitioned Pallas decode (each device scatters its
    local batch shard against the replicated reference) is bit-identical
    to the XLA scatter on the virtual 8-device mesh — VERDICT r1 item 6:
    the fast decode survives multi-device scale-out."""
    from blendjax.parallel import create_mesh

    mesh = create_mesh({"data": -1})
    n = int(np.prod(list(mesh.shape.values())))
    assert n == 8  # conftest forces 8 virtual CPU devices
    ref, frames = _frames(n=8, shape=(64, 64), seed=17)
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    rt = tile_ref(ref, 16)

    sharded = np.asarray(
        decode_tile_delta(
            rt, idx, tiles, ref.shape, use_pallas=True, mesh=mesh
        )
    )
    xla = np.asarray(
        decode_tile_delta(rt, idx, tiles, ref.shape, use_pallas=False)
    )
    np.testing.assert_array_equal(sharded, xla)
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(sharded[i], f)

    # auto-select: multi-device without a mesh stays on the XLA path;
    # with a mesh whose axis divides B it takes the sharded Pallas path
    # on TPU (off-TPU auto-select is always False; decide statically)
    from blendjax.data import StreamDataPipeline

    pipe = StreamDataPipeline(iter(()), batch_size=8, sharding=None)
    assert pipe.tiles._decode_mesh() == (None, "data")


def test_pipeline_decode_mesh_resolves_from_sharding():
    """StreamDataPipeline threads (mesh, axis) from its batch sharding
    into the decode jit, so the sharded Pallas path engages on meshes."""
    from blendjax.data import StreamDataPipeline
    from blendjax.parallel import batch_sharding, create_mesh

    mesh = create_mesh({"data": -1})
    pipe = StreamDataPipeline(
        iter(()), batch_size=8, sharding=batch_sharding(mesh)
    )
    got_mesh, axis = pipe.tiles._decode_mesh()
    assert got_mesh is mesh and axis == "data"


def test_multihost_tile_stream_assembles_and_decodes_globally():
    """Tile streams x multihost (VERDICT r1 item 4): batch-leading tile
    fields assemble into global arrays (degenerate 1-process case of
    make_array_from_process_local_data), refs replicate globally, and
    the decode runs shard-locally on the mesh — bit-exact, raw-tile and
    per-row-palette wire variants both."""
    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        PALETTE_SUFFIX,
        TILEIDX_SUFFIX,
        TILEPAL4_SUFFIX,
        TILEPAL8_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
        palettize_tiles,
    )
    from blendjax.parallel import batch_sharding, create_mesh

    mesh = create_mesh({"data": -1})
    sharding = batch_sharding(mesh)
    # Flat background + solid-color edits: the changed tiles then hold
    # few distinct colors, so the palette wire variant engages.
    rng = np.random.default_rng(9)
    ref = np.full((32, 32, 4), (40, 80, 120, 255), np.uint8)
    colors = rng.integers(0, 255, (8, 4), np.uint8)
    frames = []
    for i in range(16):
        img = ref.copy()
        y, x = rng.integers(0, 24, 2)
        img[y: y + 8, x: x + 8] = colors[i % 8]
        frames.append(img)
    enc = TileDeltaEncoder(ref, tile=16)

    def tile_msg(batch, with_ref, palette):
        deltas = [tuple(a.copy() for a in enc.encode(f)) for f in batch]
        idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
        msg = {
            "_prebatched": True, "btid": 0,
            "image" + TILEIDX_SUFFIX: idx,
            "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
            "frameid": np.arange(len(batch)),
        }
        if palette:
            packed, pal, bits = palettize_tiles(tiles, max_colors=256)
            suffix = TILEPAL4_SUFFIX if bits == 4 else TILEPAL8_SUFFIX
            msg["image" + suffix] = packed
            msg["image" + PALETTE_SUFFIX] = pal
        else:
            msg["image" + TILES_SUFFIX] = tiles
        if with_ref:
            msg["image" + TILEREF_SUFFIX] = ref
        return msg

    def messages():
        yield tile_msg(frames[0:8], True, palette=False)
        yield tile_msg(frames[8:16], False, palette=True)

    with StreamDataPipeline(
        messages(), batch_size=8, sharding=sharding, multihost=True
    ) as pipe:
        got = list(pipe)

    assert len(got) == 2
    for start, b in zip((0, 8), got):
        img = np.asarray(b["image"])
        assert img.shape == (8, 32, 32, 4)
        # decoded field is a global array sharded over the data axis
        assert b["image"].sharding.is_equivalent_to(sharding, 4)
        for i in range(8):
            np.testing.assert_array_equal(img[i], frames[start + i])


def test_multihost_tiles_chunked_superbatch():
    """chunk>1 x multihost (single-process SPMD stand-in on the virtual
    8-device mesh): K compatible tile batches assemble into ONE global
    (K, B, ...) superbatch, chunk axis replicated / batch axis sharded,
    decoded bit-exactly in one call (VERDICT r2 item 4; the true
    2-process case is tests/test_multiprocess.py)."""
    from jax.sharding import PartitionSpec as P

    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
    )
    from blendjax.parallel import batch_sharding, create_mesh

    mesh = create_mesh({"data": -1})
    ref, frames = _frames(n=32, shape=(32, 32), seed=12)
    enc = TileDeltaEncoder(ref, tile=16)
    B = 8  # divisible by the virtual 8-device mesh

    def batch_msg(lo, with_ref):
        deltas = [
            tuple(a.copy() for a in enc.encode(f))
            for f in frames[lo: lo + B]
        ]
        idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
        msg = {
            "_prebatched": True, "btid": 0,
            "image" + TILEIDX_SUFFIX: idx,
            "image" + TILES_SUFFIX: tiles,
            "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
            "frameid": np.arange(B) + lo,
        }
        if with_ref:
            msg["image" + TILEREF_SUFFIX] = ref
        return msg

    def messages():
        for n in range(4):  # 2 groups of K=2 batches of 8 frames
            yield batch_msg(B * n, with_ref=n == 0)

    with StreamDataPipeline(
        messages(), batch_size=B, sharding=batch_sharding(mesh),
        multihost=True, chunk=2,
    ) as pipe:
        got = list(pipe)
    assert [np.asarray(b["image"]).shape for b in got] == [
        (2, B, 32, 32, 4)
    ] * 2
    from blendjax.testing.equivalence import normalized_spec

    for b in got:
        # canonicalization-proof layout compare (some jax releases
        # deliver P(None, 'data') as P(None, ('data',)))
        assert normalized_spec(b["image"].sharding) == (None, "data")
        img = np.asarray(b["image"])
        fid = np.asarray(b["frameid"])
        for k in range(2):
            for i in range(B):
                np.testing.assert_array_equal(
                    img[k, i], frames[int(fid[k, i])]
                )

    # Stream end mid-group: the trailing short group flushes as K'=1
    # (the same lockstep rule — every process ends together under SPMD).
    def three_batches():
        for n in range(3):
            yield batch_msg(B * n, with_ref=n == 0)

    with StreamDataPipeline(
        three_batches(), batch_size=B, sharding=batch_sharding(mesh),
        multihost=True, chunk=2,
    ) as pipe:
        tail = list(pipe)
    assert [np.asarray(b["image"]).shape for b in tail] == [
        (2, B, 32, 32, 4), (1, B, 32, 32, 4)
    ]
    short = np.asarray(tail[1]["image"])
    for i in range(B):
        np.testing.assert_array_equal(
            short[0, i], frames[int(np.asarray(tail[1]["frameid"])[0, i])]
        )


@pytest.mark.tpu
def test_pallas_scatter_decode_on_real_tpu():
    """Non-interpret lowering of the scatter kernel on actual hardware
    (run with BLENDJAX_TEST_TPU=1 pytest -m tpu)."""
    ref, frames = _frames(n=4, shape=(64, 64), seed=21)
    enc = TileDeltaEncoder(ref, tile=16)
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    out = np.asarray(
        decode_tile_delta(
            jax.device_put(np.asarray(tile_ref(ref, 16))),
            jax.device_put(idx), jax.device_put(tiles),
            ref.shape, use_pallas=True,
        )
    )
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(out[i], f)


@pytest.mark.tpu
def test_spatial_decode_on_real_tpu():
    """Non-interpret lowering of the direct-spatial kernel on actual
    hardware (run with BLENDJAX_TEST_TPU=1 pytest -m tpu)."""
    ref, frames = _frames(n=4, shape=(64, 64), seed=25)
    enc = TileDeltaEncoder(ref, tile=(16, 32))
    deltas = [tuple(a.copy() for a in enc.encode(f)) for f in frames]
    idx, tiles = pack_batch(deltas, enc.num_tiles)
    out = np.asarray(
        decode_tile_delta(
            jax.device_put(np.asarray(tile_ref(ref, (16, 32)))),
            jax.device_put(idx), jax.device_put(tiles),
            ref.shape, use_pallas=True,
        )
    )
    for i, f in enumerate(frames):
        np.testing.assert_array_equal(out[i], f)


def test_tile_stream_survives_producer_respawn():
    """Kill a tile-encoding producer mid-stream with respawn=True: the
    respawned process re-sends its reference image (first-message rule),
    so decode state stays correct per (field, btid)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=7,
        respawn=True,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4,
            # generous timeout: the respawned interpreter needs a few
            # seconds to boot on a loaded core before publishing resumes
            # (3 retries x this budget before the stream gives up)
            launcher=launcher, timeoutms=15000,
        ) as pipe:
            it = iter(pipe)
            first = next(it)
            launcher.processes[0].terminate()
            # Drain queued pre-kill batches (SNDHWM + RCVHWM + kernel TCP
            # buffers hold many of these small messages) until the
            # respawned producer's restarted frame sequence shows up
            # (frameids reset to 1..4); bounded so a broken respawn fails
            # rather than spins.
            after = []
            for _ in range(500):
                b = next(it)
                after.append(b)
                if int(np.asarray(b["frameid"])[0]) == 1:
                    break
            else:
                raise AssertionError("never saw the respawned producer's "
                                     "restarted frame sequence")
    # every frame (pre- and post-respawn) reconstructs bit-exact against
    # a local re-render: the producer is deterministic from seed 7, and
    # the respawned process replays the same sequence from frame 1.
    fmax = max(
        int(f) for b in [first, *after] for f in np.asarray(b["frameid"])
    )
    scene = CubeScene(shape=(64, 64), seed=7)
    local = {}
    for f in range(1, fmax + 1):
        scene.step(f)
        local[f] = scene.render().copy()
    checked = 0
    for b in [first, *after]:
        img = np.asarray(b["image"])
        for i, f in enumerate(np.asarray(b["frameid"])):
            np.testing.assert_array_equal(img[i], local[int(f)])
            checked += 1
    assert checked >= 8  # at least first + the post-respawn batch


def test_np_decoder_handles_non_suffix_sentinels():
    """decode_tile_delta_np pairs indices and tiles positionally (like
    the device decoder), even when sentinels are not a trailing suffix."""
    from blendjax.ops.tiles import decode_tile_delta_np

    ref, frames = _frames(n=1, shape=(32, 32), seed=17)
    img = frames[0]
    enc = TileDeltaEncoder(ref, tile=16)
    fi, ft = enc.encode(img)
    fi, ft = fi.copy(), ft.copy()
    n = enc.num_tiles
    # interleave sentinels before real entries
    idx = np.full((1, len(fi) * 2), n, np.int32)
    tiles = np.zeros((1, len(fi) * 2, 16, 16, 4), np.uint8)
    idx[0, 1::2] = fi
    tiles[0, 1::2] = ft
    out = decode_tile_delta_np(ref, idx, tiles, tile=16)
    np.testing.assert_array_equal(out[0], img)


def test_keyframe_interval_lets_late_consumer_sync():
    """A consumer that missed the initial reference (simulated by a
    stream whose first tile messages carry no ref) skips until a
    keyframe arrives, then decodes exactly — the multi-worker /
    multi-epoch story for tile streams."""
    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
    )

    ref, frames = _frames(n=12, shape=(32, 32), seed=19)
    enc = TileDeltaEncoder(ref, tile=16)

    def messages():
        for start in range(0, 12, 4):
            batch = frames[start:start + 4]
            deltas = [tuple(a.copy() for a in enc.encode(f)) for f in batch]
            idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
            msg = {
                "_prebatched": True,
                "btid": 0,
                "image" + TILEIDX_SUFFIX: idx,
                "image" + TILES_SUFFIX: tiles,
                "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
                "frameid": np.arange(start, start + 4),
            }
            if start == 8:  # ref arrives only in the LAST message
                msg["image" + TILEREF_SUFFIX] = ref
            yield msg

    pipe = StreamDataPipeline(messages(), batch_size=4)
    got = list(pipe)
    # first two batches skipped (no ref yet); the keyframe batch decodes
    assert len(got) == 1
    img = np.asarray(got[0]["image"])
    for i, f in enumerate(np.asarray(got[0]["frameid"])):
        np.testing.assert_array_equal(img[i], frames[int(f)])


def test_torch_adapter_multi_epoch_tile_stream():
    """Epoch 2 over the same dataset instance still decodes: refs persist
    on the instance after the producer's one-time ref message."""
    from blendjax.data.torch_compat import RemoteIterableDataset
    from blendjax.launcher import PythonProducerLauncher

    import os as _os

    producer = _os.path.join(
        _os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        seed=8,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16", "--ref-interval", "0"]  # ref sent ONCE
        ],
    ) as launcher:
        ds = RemoteIterableDataset(
            launcher.addresses["DATA"], max_items=8, timeoutms=30_000
        )
        epoch1 = list(ds)
        epoch2 = list(ds)  # fresh iterator; refs persist on the instance
    # max_items=8 counts ITEMS (2 producer batches of 4), per epoch
    assert len(epoch1) == 8 and len(epoch2) == 8
    for it in epoch2:
        assert it["image"].shape == (64, 64, 4)


def test_multi_producer_tile_fan_in_bit_exact():
    """Two tile-encoding producers fan into one consumer: per-(field,
    btid) references keep every interleaved batch decoding against the
    right producer's ref, bit-exact per seed."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    seed = 31
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=2,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16"]
        ] * 2,
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=4, timeoutms=30_000
        ) as pipe:
            it = iter(pipe)
            batches = [next(it) for _ in range(8)]
    # launcher seeds instances seed+0, seed+1; re-render both locally
    local = {}
    for inst in (0, 1):
        scene = CubeScene(shape=(64, 64), seed=seed + inst)
        for f in range(1, 80):
            scene.step(f)
            local[(inst, f)] = scene.render().copy()
    seen_btids = set()
    for b in batches:
        btid = int(np.asarray(b["btid"]))
        seen_btids.add(btid)
        img = np.asarray(b["image"])
        for i, f in enumerate(np.asarray(b["frameid"])):
            np.testing.assert_array_equal(img[i], local[(btid, int(f))])
    assert seen_btids == {0, 1}  # fair fan-in actually interleaved


def test_chunked_pipeline_superbatches_bit_exact():
    """chunk=4: the pipeline yields (4, B, H, W, C) superbatches, one
    transfer + one decode per group, still bit-exact per frame."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    seed = 41
    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    sharding = NamedSharding(mesh, P("data"))
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"], batch_size=8, chunk=4,
            sharding=sharding, timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            supers = [next(it) for _ in range(2)]
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 128):
        scene.step(f)
        local[f] = scene.render().copy()
    for sb in supers:
        assert sb["image"].shape == (4, 8, 64, 64, 4)
        assert sb["frameid"].shape == (4, 8)
        # chunk axis replicated, batch axis sharded over the mesh
        assert sb["image"].sharding.spec == P(None, "data")
        img = np.asarray(sb["image"])
        fid = np.asarray(sb["frameid"])
        for k in range(4):
            for i in range(8):
                np.testing.assert_array_equal(
                    img[k, i], local[int(fid[k, i])]
                )


def test_chunked_step_equals_sequential_steps():
    """One jitted scan over a (K, B, ...) superbatch produces the same
    final params as K sequential per-batch steps (SGD)."""
    import optax

    from blendjax.models import CubeRegressor
    from blendjax.parallel import batch_sharding, create_mesh
    from blendjax.train import (
        make_chunked_supervised_step,
        make_supervised_step,
        make_train_state,
    )

    mesh = create_mesh({"data": -1})
    sh = batch_sharding(mesh)
    rng = np.random.default_rng(3)
    K, B = 3, 4
    images = rng.integers(0, 255, (K, B, 32, 32, 4), np.uint8)
    xys = (rng.random((K, B, 8, 2)) * 32).astype(np.float32)
    s0 = make_train_state(
        CubeRegressor(), images[0], mesh=mesh, optimizer=optax.sgd(0.01)
    )
    seq = make_supervised_step(mesh=mesh, batch_sharding=sh, donate=False)
    chunked = make_chunked_supervised_step(donate=False)

    s_seq = s0
    seq_losses = []
    for k in range(K):
        s_seq, m = seq(s_seq, {"image": images[k], "xy": xys[k]})
        seq_losses.append(float(m["loss"]))
    s_chk, mc = chunked(s0, {"image": images, "xy": xys})
    np.testing.assert_allclose(
        np.asarray(mc["loss"]), seq_losses, rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        ),
        s_seq.params, s_chk.params,
    )


def test_fused_tile_step_matches_decode_then_step():
    """emit_packed + make_fused_tile_step trains bit-identically to the
    decode-then-chunked-step pipeline over the same synthetic tile
    stream (same SGD trajectory, same losses)."""
    import optax

    from blendjax.data import StreamDataPipeline
    from blendjax.models import CubeRegressor
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
    )
    from blendjax.train import (
        make_chunked_supervised_step,
        make_fused_tile_step,
        make_train_state,
    )

    ref, frames = _frames(n=8, shape=(32, 32), seed=11)
    rng = np.random.default_rng(5)
    xys = (rng.random((4, 2, 8, 2)) * 32).astype(np.float32)
    enc = TileDeltaEncoder(ref, tile=16)

    def messages():
        for g in range(4):  # 4 batches of 2 frames
            batch = frames[2 * g: 2 * g + 2]
            deltas = [tuple(a.copy() for a in enc.encode(f)) for f in batch]
            idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
            msg = {
                "_prebatched": True, "btid": 0,
                "image" + TILEIDX_SUFFIX: idx,
                "image" + TILES_SUFFIX: tiles,
                "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
                "xy": xys[g],
            }
            if g == 0:
                msg["image" + TILEREF_SUFFIX] = ref
            yield msg

    s0 = make_train_state(
        CubeRegressor(), frames[0][None].repeat(2, 0),
        optimizer=optax.sgd(0.01),
    )

    with StreamDataPipeline(messages(), batch_size=2, chunk=2) as pipe:
        decoded = list(pipe)
    assert [np.asarray(b["image"]).shape for b in decoded] == [
        (2, 2, 32, 32, 4)
    ] * 2
    chunked = make_chunked_supervised_step(donate=False)
    s_ref = s0
    ref_losses = []
    for b in decoded:
        s_ref, m = chunked(s_ref, {"image": b["image"], "xy": b["xy"]})
        ref_losses.extend(np.asarray(m["loss"]).tolist())

    with StreamDataPipeline(
        messages(), batch_size=2, chunk=2, emit_packed=True
    ) as pipe:
        packed_batches = list(pipe)
    assert all("_packed" in b for b in packed_batches)
    fused = make_fused_tile_step(donate=False)
    s_fused = s0
    fused_losses = []
    for b in packed_batches:
        s_fused, m = fused(s_fused, b)
        fused_losses.extend(np.asarray(m["loss"]).tolist())

    np.testing.assert_allclose(fused_losses, ref_losses, rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-8
        ),
        s_ref.params, s_fused.params,
    )


def test_palettize_roundtrip_and_fallbacks():
    """Palette compression: 4-bit for <=16 colors, 8-bit for <=256, None
    beyond; native and numpy passes agree; expansion is bit-exact."""
    from blendjax.ops.tiles import (
        expand_palette_tiles_np,
        palettize_tiles,
    )

    rng = np.random.default_rng(23)

    def tiles_with_colors(ncolors):
        pal = rng.integers(0, 255, (ncolors, 4), np.uint8)
        idx = rng.integers(0, ncolors, (2, 5, 16, 16))
        return pal[idx]

    t12 = tiles_with_colors(12)
    packed, pal, bits = palettize_tiles(t12)
    assert bits == 4 and packed.shape == (2, 5, 128) and pal.shape == (16, 4)
    np.testing.assert_array_equal(
        expand_palette_tiles_np(packed, pal, 4, 16, 4), t12
    )

    t100 = tiles_with_colors(100)
    packed, pal, bits = palettize_tiles(t100)
    assert bits == 8 and packed.shape == (2, 5, 256) and pal.shape == (256, 4)
    np.testing.assert_array_equal(
        expand_palette_tiles_np(packed, pal, 8, 16, 4), t100
    )

    # >256 colors: every pixel unique in one tile region
    many = np.arange(2 * 5 * 16 * 16 * 4, dtype=np.uint32)
    many = (many % 251 * 7919 + many).astype(np.uint32)
    tmany = many.view(np.uint8)[: 2 * 5 * 16 * 16 * 4].reshape(2, 5, 16, 16, 4)
    assert palettize_tiles(tmany) is None

    # numpy fallback agrees with native
    from blendjax._native import load_palettize

    if load_palettize() is not None:
        import os as _os

        native_res = palettize_tiles(t12)
        _os.environ["BLENDJAX_NO_NATIVE"] = "1"
        try:
            # the loader caches; emulate numpy path by calling internals
            from blendjax._native import build as _b

            _b._CACHE.pop("palettize", None)
            numpy_res = palettize_tiles(t12)
        finally:
            del _os.environ["BLENDJAX_NO_NATIVE"]
            _b._CACHE.pop("palettize", None)
        np.testing.assert_array_equal(
            expand_palette_tiles_np(*native_res[:2], native_res[2], 16, 4),
            expand_palette_tiles_np(*numpy_res[:2], numpy_res[2], 16, 4),
        )


def test_chunk_strict_rejects_raw_messages():
    """chunk>1 with chunk_strict=True over a stream containing a non-tile
    message fails loudly (opt-in fail-fast contract)."""
    from blendjax.data import StreamDataPipeline

    def messages():
        yield {"_batched": True, "btid": 0,
               "image": np.zeros((4, 32, 32, 4), np.uint8)}

    pipe = StreamDataPipeline(
        messages(), batch_size=4, chunk=4, chunk_strict=True
    )
    with pytest.raises(RuntimeError, match="all-tile"):
        list(pipe)


def test_chunk_mode_degrades_on_mixed_stream(caplog):
    """Default chunk>1 behavior on a mixed stream: the in-flight tile
    group flushes, the raw batch passes through as a K'=1 superbatch with
    one warning, and every frame still reconstructs bit-exactly."""
    import logging

    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
    )

    ref, frames = _frames(n=8, shape=(32, 32), seed=4)
    enc = TileDeltaEncoder(ref, tile=16)
    raw = np.stack(frames[4:6])  # the misconfigured producer's batch

    def tile_msg(batch, with_ref):
        deltas = [tuple(a.copy() for a in enc.encode(f)) for f in batch]
        idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
        msg = {
            "_prebatched": True, "btid": 0,
            "image" + TILEIDX_SUFFIX: idx,
            "image" + TILES_SUFFIX: tiles,
            "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
        }
        if with_ref:
            msg["image" + TILEREF_SUFFIX] = ref
        return msg

    def messages():
        yield tile_msg(frames[0:2], True)   # group member 1
        yield {"_batched": True, "btid": 1, "image": raw}  # intruder
        yield tile_msg(frames[2:4], False)  # group member after flush
        yield tile_msg(frames[6:8], False)

    from blendjax.utils.metrics import metrics

    degraded0 = metrics.counters.get("tiles.degraded_groups", 0)
    with caplog.at_level(logging.WARNING, logger="blendjax.data"):
        pipe = StreamDataPipeline(messages(), batch_size=2, chunk=2)
        got = list(pipe)

    # flushed group of 1, the K'=1 raw superbatch, then a full group of 2
    shapes = [np.asarray(b["image"]).shape for b in got]
    assert shapes == [
        (1, 2, 32, 32, 4), (1, 2, 32, 32, 4), (2, 2, 32, 32, 4)
    ]
    np.testing.assert_array_equal(np.asarray(got[0]["image"])[0, 0], frames[0])
    np.testing.assert_array_equal(np.asarray(got[0]["image"])[0, 1], frames[1])
    np.testing.assert_array_equal(np.asarray(got[1]["image"])[0], raw)
    np.testing.assert_array_equal(np.asarray(got[2]["image"])[0, 0], frames[2])
    np.testing.assert_array_equal(np.asarray(got[2]["image"])[1, 1], frames[7])
    warns = [r for r in caplog.records if "non-tile message" in r.message]
    assert len(warns) == 1
    # the degradation is countable, not just logged (fleet visibility)
    assert (
        metrics.counters.get("tiles.degraded_groups", 0) - degraded0 == 1
    )


def test_prebatched_size_mismatch_warns_once(caplog):
    """A producer batch size differing from the pipeline's passes through
    ragged, flagged by a single warning."""
    import logging

    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        TILEIDX_SUFFIX,
        TILEREF_SUFFIX,
        TILES_SUFFIX,
        TILESHAPE_SUFFIX,
    )

    ref, frames = _frames(n=6, shape=(32, 32), seed=2)
    enc = TileDeltaEncoder(ref, tile=16)

    def messages():
        for start in (0, 3):
            batch = frames[start:start + 3]  # producer batches of 3
            deltas = [tuple(a.copy() for a in enc.encode(f)) for f in batch]
            idx, tiles = pack_batch(deltas, enc.num_tiles, capacity=4)
            msg = {
                "_prebatched": True, "btid": 0,
                "image" + TILEIDX_SUFFIX: idx,
                "image" + TILES_SUFFIX: tiles,
                "image" + TILESHAPE_SUFFIX: [32, 32, 4, 16],
            }
            if start == 0:
                msg["image" + TILEREF_SUFFIX] = ref
            yield msg

    with caplog.at_level(logging.WARNING, logger="blendjax.data"):
        pipe = StreamDataPipeline(messages(), batch_size=8)  # != 3
        got = list(pipe)
    assert [b["image"].shape[0] for b in got] == [3, 3]  # ragged pass-through
    for start, b in zip((0, 3), got):
        img = np.asarray(b["image"])
        for i in range(3):
            np.testing.assert_array_equal(img[i], frames[start + i])
    warns = [r for r in caplog.records if "prebatched" in r.message]
    assert len(warns) == 1  # warned once, not per message


# -- full-frame palette codec (the non-sparse path) --------------------------


def test_palettize_frames_roundtrip_all_widths_and_overflow():
    """Per-frame full-frame palettes: the widest FRAME picks 2/4/8-bit
    indices; every width round-trips bit-exact (numpy and device twins),
    and a single >256-color frame fails the whole batch to raw."""
    from blendjax.ops.tiles import (
        expand_palette_frames,
        expand_palette_frames_np,
        palettize_frames,
    )

    rng = np.random.default_rng(0)
    h, w = 16, 24

    def roundtrip(frames, want_bits, want_len):
        packed, pal, bits = palettize_frames(frames)
        assert bits == want_bits and packed.shape == (len(frames), want_len)
        assert pal.ndim == 3 and pal.shape[0] == len(frames)  # per-frame
        np.testing.assert_array_equal(
            expand_palette_frames_np(packed, pal, bits, h, w, 4), frames
        )
        np.testing.assert_array_equal(
            np.asarray(jax.jit(
                lambda p, q: expand_palette_frames(p, q, bits, h, w, 4)
            )(packed, pal)),
            frames,
        )

    # <=4 colors per frame -> 2-bit (16x)
    tiny = np.repeat(
        rng.integers(0, 4, (4, h, w, 1), np.uint8) * 60, 4, axis=-1
    )
    roundtrip(tiny, 2, h * w // 4)
    # <=16 colors per frame -> 4-bit (8x); per-frame tables mean DISTINCT
    # colors across frames still fit (here ~64 batch-wide)
    few = np.stack([
        np.repeat(
            rng.integers(0, 16, (h, w, 1), np.uint8) * 13 + i * 17,
            4, axis=-1,
        )
        for i in range(4)
    ])
    roundtrip(few, 4, h * w // 2)
    # <=256 colors in one frame -> 8-bit (4x)
    some = np.repeat(
        rng.integers(0, 200, (4, h, w, 1), np.uint8), 4, axis=-1
    )
    roundtrip(some, 8, h * w)
    # >256 colors in any frame -> None (ship raw)
    many = rng.integers(0, 255, (2, 32, 32, 4), np.uint8)
    assert palettize_frames(many) is None


def test_stream_pipeline_pal_encoding_end_to_end():
    """--encoding pal -> ONE packed transfer per batch, decoded by a
    device gather to bit-exact full frames (the lossless non-sparse
    codec; VERDICT r3 next #2)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene
    from blendjax.utils.metrics import metrics as reg

    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    sharding = NamedSharding(mesh, P("data"))
    seed = 7
    reg.reset()
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "pal"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=8,
            sharding=sharding,
            timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            batches = [next(it) for _ in range(3)]

    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 8 * len(batches) + 1):
        scene.step(f)
        local[f] = scene.render().copy()

    for b in batches:
        assert b["image"].shape == (8, 64, 64, 4)
        assert b["image"].dtype == np.uint8
        img = np.asarray(b["image"])
        for i, f in enumerate(np.asarray(b["frameid"])):
            np.testing.assert_array_equal(img[i], local[int(f)])
    # wire accounting: the codec actually compressed (cube scene fits
    # pal4 => ~8x; assert a conservative 3x to stay weather-proof)
    wire = reg.counters.get("pal.wire_bytes", 0)
    decoded = reg.counters.get("pal.decoded_bytes", 0)
    assert decoded and wire and decoded / wire > 3.0


def test_pal_stream_chunk_mode_superbatch_bit_exact():
    """chunk>1 coalesces K packed pal batches into ONE stacked transfer
    decoded to a (K, B, ...) superbatch — bit-exact per frame, each
    group member through its own palette (the non-sparse row's
    op-latency fix: K transfers + K dispatches collapse K-fold)."""
    from blendjax.data import StreamDataPipeline
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    mesh = Mesh(np.array(jax.devices()).reshape(-1), axis_names=("data",))
    sharding = NamedSharding(mesh, P("data"))
    seed = 3
    with PythonProducerLauncher(
        script=PRODUCER,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "8", "--encoding", "pal"]
        ],
    ) as launcher:
        with StreamDataPipeline(
            launcher.addresses["DATA"],
            batch_size=8,
            sharding=sharding,
            chunk=2,
            timeoutms=30_000,
        ) as pipe:
            it = iter(pipe)
            sb = next(it)
    assert sb["image"].shape == (2, 8, 64, 64, 4)  # (K, B, ...)
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 17):
        scene.step(f)
        local[f] = scene.render().copy()
    img = np.asarray(sb["image"]).reshape(16, 64, 64, 4)
    for i, f in enumerate(np.asarray(sb["frameid"]).reshape(-1)):
        np.testing.assert_array_equal(img[i], local[int(f)])


def test_pal_stream_multihost_host_expand_fallback():
    """Full-frame palette batches in a multihost pipeline stay CORRECT
    via the host-expand fallback: frames decode on the host and ride
    the standard global-assembly path, bit-exact."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from blendjax.data import StreamDataPipeline
    from blendjax.ops.tiles import (
        FRAMEPAL8_SUFFIX,
        FRAMESHAPE_SUFFIX,
        PALETTE_SUFFIX,
        palettize_frames,
    )
    from blendjax.parallel import batch_sharding, create_mesh

    n = len(jax.devices())
    mesh = create_mesh({"data": -1})
    rng = np.random.default_rng(5)
    frames = np.repeat(
        rng.integers(0, 40, (n, 16, 24, 1), np.uint8) * 6, 4, axis=-1
    )
    out = palettize_frames(frames)
    assert out is not None
    packed, pal, bits = out
    from blendjax.ops.tiles import FRAMEPAL_SUFFIXES

    suffix = FRAMEPAL_SUFFIXES[bits]
    msg = {
        "_prebatched": True, "btid": 0,
        "image" + suffix: packed,
        "xy": np.zeros((n, 8, 2), np.float32),
        "image" + PALETTE_SUFFIX: pal,
        "image" + FRAMESHAPE_SUFFIX: np.array([16, 24, 4, bits], np.int32),
    }
    with StreamDataPipeline(
        iter([msg]), batch_size=n, sharding=batch_sharding(mesh),
        multihost=True,
    ) as pipe:
        (b,) = list(pipe)
    assert b["image"].shape == (n, 16, 24, 4)
    np.testing.assert_array_equal(np.asarray(b["image"]), frames)


# -- run-length ("ndr") tile-group codec -------------------------------------


def test_rle_encode_expand_roundtrip_device_equals_host():
    """rle_expand_packed (the in-jit scan/gather) and the numpy twin
    reconstruct bit-exactly, for pixel runs (isz=4) and byte runs
    (isz=1) including runs past the uint16 split point."""
    import jax

    from blendjax.ops.tiles import (
        rle_encode_rows,
        rle_expand_packed,
        rle_expand_packed_np,
    )

    rng = np.random.default_rng(0)
    img = np.zeros((4, 48, 48, 4), np.uint8)
    img[:, 8:20, 4:40] = rng.integers(0, 5, (4, 12, 36, 4), dtype=np.uint8)
    flat = np.zeros((2, 70_000), np.uint8)
    flat[1, 500:700] = 9  # one >65535 background run split at encode
    for arr in (img, flat):
        buf, cap, isz = rle_encode_rows(arr)
        host = rle_expand_packed_np(buf, arr.shape, isz, cap)
        np.testing.assert_array_equal(host, arr)
        dev = jax.jit(
            rle_expand_packed, static_argnums=(1, 2, 3)
        )(buf, arr.shape, isz, cap)
        np.testing.assert_array_equal(np.asarray(dev), arr)


def test_rle_validation_guards_device_plan():
    from blendjax.ops.tiles import (
        rle_encode_rows,
        rle_validate_packed,
    )

    img = np.zeros((4, 32, 32, 4), np.uint8)
    img[:, 4:12, 4:12] = 3
    buf, cap, isz = rle_encode_rows(img)
    rle_validate_packed(buf, img.shape, isz, cap)  # honest buffer passes
    with pytest.raises(ValueError, match="does not match"):
        rle_validate_packed(buf[:, :-4], img.shape, isz, cap)
    bad = buf.copy()
    bad[:, cap * isz:] = 0  # wipe the run planes: rows under-declare
    with pytest.raises(ValueError, match="declared"):
        rle_validate_packed(bad, img.shape, isz, cap)
    with pytest.raises(ValueError, match="out of bounds"):
        rle_validate_packed(buf, img.shape, isz, 0)


def test_decode_packed_pal_batch_expands_rle_groups():
    """The shared decode entry point expands deferred run buffers
    FIRST, so a run-packed raw frame (empty pal_groups) and a
    run-packed palette plane both restore inside one jit."""
    import jax

    from blendjax.ops.tiles import (
        NDR_SUFFIX,
        decode_packed_pal_batch,
        pack_fields,
        rle_encode_rows,
    )

    img = np.zeros((4, 32, 32, 4), np.uint8)
    img[:, 10:20, 10:20] = 6
    xy = np.arange(4 * 8 * 2, dtype=np.float32).reshape(4, 8, 2)
    buf, cap, isz = rle_encode_rows(img)
    packed, spec = pack_fields({"image" + NDR_SUFFIX: buf, "xy": xy})
    rle_groups = (("image", (img.shape, isz, cap)),)
    out = jax.jit(
        decode_packed_pal_batch,
        static_argnames=("spec", "pal_groups", "rle_groups"),
    )(packed, spec=spec, pal_groups=(), rle_groups=rle_groups)
    np.testing.assert_array_equal(np.asarray(out["image"]), img)
    np.testing.assert_array_equal(np.asarray(out["xy"]), xy)
