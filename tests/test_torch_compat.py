"""Migration adapter: blendtorch-shaped torch DataLoader over blendjax
transport (reference ``tests/test_dataset.py:11-33`` streams 16 items into
4 batches through DataLoader)."""

import threading

import numpy as np
import pytest
import zmq

torch = pytest.importorskip("torch")

from blendjax.data.torch_compat import RemoteIterableDataset  # noqa: E402
from blendjax.transport import DataPublisherSocket  # noqa: E402


def test_dataloader_batches_stream():
    from torch.utils.data import DataLoader

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ds = RemoteIterableDataset([pub.addr], max_items=16, timeoutms=10000)

    def produce():
        for i in range(16):
            pub.publish(
                image=np.full((8, 8), i, np.uint8), frameid=i
            )

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    batches = list(DataLoader(ds, batch_size=4, num_workers=0))
    t.join(timeout=10)
    assert len(batches) == 4
    assert batches[0]["image"].shape == (4, 8, 8)
    assert isinstance(batches[0]["image"], torch.Tensor)
    all_frames = sorted(
        int(f) for b in batches for f in b["frameid"]
    )
    assert all_frames == list(range(16))
    pub.close()


def test_torch_adapter_decodes_tile_streams_host_side():
    """A tile-encoding producer feeds the reference-style torch dataset:
    items arrive as plain per-frame image dicts, reconstructed bit-exact
    on the host (no device involved)."""
    import os

    import numpy as np

    from blendjax.data.torch_compat import RemoteIterableDataset
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    producer = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    seed = 6
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        ds = RemoteIterableDataset(
            launcher.addresses["DATA"], max_items=10, timeoutms=30_000
        )
        items = list(ds)
    # max_items counts ITEMS (reference ``dataset.py:80-97``), not
    # producer messages: 10 items = 2.5 producer batches of 4.
    assert len(items) == 10
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 13):
        scene.step(f)
        local[f] = scene.render().copy()
    for it in items:
        assert it["image"].shape == (64, 64, 4)
        np.testing.assert_array_equal(it["image"], local[int(it["frameid"])])


def test_max_items_splits_across_workers_with_batched_producer():
    """max_items splits per-worker (8 each here) and counts items after
    batch splitting, so two DataLoader workers over a batch-4 producer
    consume exactly 16 items total (reference 4-worker split,
    ``dataset.py:80-97`` + ``tests/test_dataset.py:25``)."""
    from torch.utils.data import DataLoader

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ds = RemoteIterableDataset([pub.addr], max_items=16, timeoutms=20_000)
    stop = threading.Event()

    # Bounded sends: the PUSH socket blocks at HWM once the consumers
    # stop pulling; a 200ms SNDTIMEO lets the thread notice `stop` and
    # exit BEFORE pub.close() (closing under a blocked send aborts).
    pub.sock.setsockopt(zmq.SNDTIMEO, 200)

    def produce():
        f = 0
        while not stop.is_set():
            try:
                pub.publish(
                    _batched=True,
                    image=np.full((4, 8, 8), f % 251, np.uint8),
                    frameid=np.arange(f, f + 4),
                )
            except zmq.Again:
                continue
            f += 4

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        # spawn: forking with a live zmq socket + publisher thread in the
        # parent aborts; the reference's fork-based workers never carried
        # parent-side sockets (its launcher owns the producers).
        batches = list(
            DataLoader(
                ds, batch_size=4, num_workers=2,
                multiprocessing_context="spawn",
            )
        )
    finally:
        stop.set()
        t.join(timeout=10)
        pub.close()
    assert sum(b["image"].shape[0] for b in batches) == 16
    assert all(b["image"].shape[1:] == (8, 8) for b in batches)


def test_max_items_cap_with_recording(tmp_path):
    """Recording tees consumed messages while the item cap stops the
    stream mid-message; the recording replays at least the capped items
    (reference ``dataset.py:53-58,100-103``)."""
    from blendjax.data.batcher import HostIngest
    from blendjax.data.replay import ReplayStream

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    prefix = str(tmp_path / "rec")
    ds = RemoteIterableDataset(
        [pub.addr], max_items=6, timeoutms=20_000,
        record_path_prefix=prefix,
    )
    stop = threading.Event()

    # Bounded sends: the PUSH socket blocks at HWM once the consumers
    # stop pulling; a 200ms SNDTIMEO lets the thread notice `stop` and
    # exit BEFORE pub.close() (closing under a blocked send aborts).
    pub.sock.setsockopt(zmq.SNDTIMEO, 200)

    def produce():
        f = 0
        while not stop.is_set():
            try:
                pub.publish(
                    _batched=True,
                    image=np.full((4, 8, 8), f % 251, np.uint8),
                    frameid=np.arange(f, f + 4),
                )
            except zmq.Again:
                continue
            f += 4

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        items = list(ds)
    finally:
        stop.set()
        t.join(timeout=10)
        pub.close()
    assert len(items) == 6
    assert [int(i["frameid"]) for i in items] == list(range(6))
    replayed = [
        item
        for msg in ReplayStream(prefix + "_00.bjr")
        if msg.pop("_batched", False) or True
        for item in HostIngest._batched_views(msg)
    ]
    assert len(replayed) >= 6
    for orig, rep in zip(items, replayed):
        np.testing.assert_array_equal(orig["image"], rep["image"])


def test_torch_adapter_decodes_pal_streams_host_side():
    """A full-frame palette producer (--encoding pal) feeds the
    reference-style torch dataset: items arrive as plain per-frame
    image dicts, decoded bit-exact on the host (stateless — no
    reference image involved)."""
    import os

    import numpy as np

    from blendjax.data.torch_compat import RemoteIterableDataset
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    producer = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    seed = 9
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "pal"]
        ],
    ) as launcher:
        ds = RemoteIterableDataset(
            launcher.addresses["DATA"], max_items=8, timeoutms=30_000
        )
        items = list(ds)
    assert len(items) == 8
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 13):
        scene.step(f)
        local[f] = scene.render().copy()
    for it in items:
        assert it["image"].shape == (64, 64, 4)
        np.testing.assert_array_equal(
            it["image"], local[int(it["frameid"])]
        )


def test_scenario_stamp_tolerated_by_collate():
    """A ``_scenario``-stamped stream (blendjax.scenario) collates
    cleanly: the stamp is dropped like ``_trace`` — it is a dict
    default_collate can't stack, and stamped/unstamped producers may
    share one fan-in."""
    from torch.utils.data import DataLoader

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ds = RemoteIterableDataset([pub.addr], max_items=8, timeoutms=10000)

    def produce():
        for i in range(8):
            pub.publish(
                image=np.full((8, 8), i, np.uint8), frameid=i,
                _scenario={"id": "easy", "ver": 1},
            )

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    batches = list(DataLoader(ds, batch_size=4, num_workers=0))
    t.join(timeout=10)
    assert len(batches) == 2
    assert "_scenario" not in batches[0]
    pub.close()
