"""Migration adapter: blendtorch-shaped torch DataLoader over blendjax
transport (reference ``tests/test_dataset.py:11-33`` streams 16 items into
4 batches through DataLoader)."""

import threading

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from blendjax.data.torch_compat import RemoteIterableDataset  # noqa: E402
from blendjax.transport import DataPublisherSocket  # noqa: E402


def test_dataloader_batches_stream():
    from torch.utils.data import DataLoader

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ds = RemoteIterableDataset([pub.addr], max_items=16, timeoutms=10000)

    def produce():
        for i in range(16):
            pub.publish(
                image=np.full((8, 8), i, np.uint8), frameid=i
            )

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    batches = list(DataLoader(ds, batch_size=4, num_workers=0))
    t.join(timeout=10)
    assert len(batches) == 4
    assert batches[0]["image"].shape == (4, 8, 8)
    assert isinstance(batches[0]["image"], torch.Tensor)
    all_frames = sorted(
        int(f) for b in batches for f in b["frameid"]
    )
    assert all_frames == list(range(16))
    pub.close()


def test_torch_adapter_decodes_tile_streams_host_side():
    """A tile-encoding producer feeds the reference-style torch dataset:
    items arrive as plain per-frame image dicts, reconstructed bit-exact
    on the host (no device involved)."""
    import os

    import numpy as np

    from blendjax.data.torch_compat import RemoteIterableDataset
    from blendjax.launcher import PythonProducerLauncher
    from blendjax.producer.sim import CubeScene

    producer = os.path.join(
        os.path.dirname(__file__), "..", "examples", "datagen",
        "cube_producer.py",
    )
    seed = 6
    with PythonProducerLauncher(
        script=producer,
        num_instances=1,
        named_sockets=["DATA"],
        seed=seed,
        instance_args=[
            ["--shape", "64", "64", "--batch", "4", "--encoding", "tile",
             "--tile", "16"]
        ],
    ) as launcher:
        ds = RemoteIterableDataset(
            launcher.addresses["DATA"], max_items=3, timeoutms=30_000
        )
        items = list(ds)
    assert len(items) == 12  # 3 messages x 4 frames
    scene = CubeScene(shape=(64, 64), seed=seed)
    local = {}
    for f in range(1, 13):
        scene.step(f)
        local[f] = scene.render().copy()
    for it in items:
        assert it["image"].shape == (64, 64, 4)
        np.testing.assert_array_equal(it["image"], local[int(it["frameid"])])
