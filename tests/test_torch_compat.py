"""Migration adapter: blendtorch-shaped torch DataLoader over blendjax
transport (reference ``tests/test_dataset.py:11-33`` streams 16 items into
4 batches through DataLoader)."""

import threading

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from blendjax.data.torch_compat import RemoteIterableDataset  # noqa: E402
from blendjax.transport import DataPublisherSocket  # noqa: E402


def test_dataloader_batches_stream():
    from torch.utils.data import DataLoader

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    ds = RemoteIterableDataset([pub.addr], max_items=16, timeoutms=10000)

    def produce():
        for i in range(16):
            pub.publish(
                image=np.full((8, 8), i, np.uint8), frameid=i
            )

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    batches = list(DataLoader(ds, batch_size=4, num_workers=0))
    t.join(timeout=10)
    assert len(batches) == 4
    assert batches[0]["image"].shape == (4, 8, 8)
    assert isinstance(batches[0]["image"], torch.Tensor)
    all_frames = sorted(
        int(f) for b in batches for f in b["frameid"]
    )
    assert all_frames == list(range(16))
    pub.close()
