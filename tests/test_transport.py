"""Transport layer tests: codecs, stream semantics, duplex, RPC.

These are hermetic (no Blender, no GPU): producers are plain Python on the
other end of real TCP sockets, per SURVEY.md §4's "fake producer" strategy.
"""

import pickle
import threading
import time

import numpy as np
import pytest

from blendjax.transport import (
    DataPublisherSocket,
    DataReceiverSocket,
    PairChannel,
    ReceiveTimeoutError,
    RpcClient,
    RpcServer,
    decode_message,
    encode_message,
)

WILD = "tcp://127.0.0.1:*"


def test_tensor_codec_roundtrip():
    msg = {
        "image": np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4),
        "xy": np.ones((5, 2), dtype=np.float32),
        "frameid": 7,
        "name": "cube",
        "nested": {"a": [1, 2, 3], "b": None},
        "weird": {1, 2, 3},  # a set: falls back to embedded pickle
        "npscalar": np.int64(42),
    }
    frames = encode_message(msg, codec="tensor")
    out = decode_message(frames)
    assert out["image"].dtype == np.uint8 and out["image"].shape == (2, 3, 4)
    np.testing.assert_array_equal(out["image"], msg["image"])
    np.testing.assert_array_equal(out["xy"], msg["xy"])
    assert out["frameid"] == 7 and out["name"] == "cube"
    assert out["nested"] == {"a": [1, 2, 3], "b": None}
    assert out["weird"] == {1, 2, 3}
    assert out["npscalar"] == 42


def test_tensor_codec_zero_size_array():
    msg = {"empty": np.zeros((0, 4), dtype=np.float32)}
    out = decode_message(encode_message(msg, codec="tensor"))
    assert out["empty"].shape == (0, 4)


def test_pickle_codec_autodetect():
    msg = {"image": np.zeros((4, 4), np.uint8), "btid": 3}
    frames = encode_message(msg, codec="pickle")
    assert len(frames) == 1
    out = decode_message(frames)
    np.testing.assert_array_equal(out["image"], msg["image"])
    assert out["btid"] == 3


def test_push_pull_stream_and_fan_in():
    pub_a = DataPublisherSocket(WILD, btid=0)
    pub_b = DataPublisherSocket(WILD, btid=1)
    recv = DataReceiverSocket([pub_a.addr, pub_b.addr], timeoutms=5000)
    img = np.random.randint(0, 255, (8, 8, 4), dtype=np.uint8)
    for i in range(4):
        pub_a.publish(image=img, frameid=i)
        pub_b.publish(image=img, frameid=i)
    seen = set()
    for _ in range(8):
        msg, raw = recv.recv()
        assert msg["image"].shape == (8, 8, 4)
        seen.add((msg["btid"], msg["frameid"]))
    assert seen == {(b, i) for b in (0, 1) for i in range(4)}
    recv.close(); pub_a.close(); pub_b.close()


def test_receiver_timeout_raises():
    pub = DataPublisherSocket(WILD, btid=0)
    recv = DataReceiverSocket([pub.addr], timeoutms=50)
    with pytest.raises(ReceiveTimeoutError):
        recv.recv()
    recv.close(); pub.close()


def test_legacy_pickle_producer_interop():
    """An unmodified btb-style producer (send_pyobj) feeds our receiver."""
    import zmq

    from blendjax.transport.channels import zmq_context

    sock = zmq_context().socket(zmq.PUSH)
    sock.setsockopt(zmq.SNDHWM, 10)
    sock.setsockopt(zmq.IMMEDIATE, 1)
    sock.bind(WILD)
    addr = sock.getsockopt_string(zmq.LAST_ENDPOINT)
    recv = DataReceiverSocket([addr], timeoutms=5000)
    payload = {"btid": 9, "image": np.ones((2, 2), np.uint8), "frameid": 0}
    sock.send(pickle.dumps(payload, protocol=3))  # exactly what send_pyobj does
    msg, _ = recv.recv()
    assert msg["btid"] == 9
    np.testing.assert_array_equal(msg["image"], payload["image"])
    recv.close(); sock.close(0)


def test_backpressure_hwm_blocks_producer():
    """With no consumer draining, a small HWM must block the producer
    (reference behavior: Blender blocks when consumers are slow,
    ``examples/datagen/Readme.md:168-175``)."""
    pub = DataPublisherSocket(WILD, btid=0, send_hwm=1)
    recv = DataReceiverSocket([pub.addr], queue_size=1, timeoutms=5000)
    # Give the connection a moment to establish so IMMEDIATE doesn't drop.
    time.sleep(0.2)
    # Payloads must dwarf kernel TCP buffers; HWM counts messages, the OS
    # buffer absorbs bytes.
    blob = np.zeros(4 * 1024 * 1024, dtype=np.uint8)
    n = 12
    sent = []

    def producer():
        for i in range(n):
            pub.publish(frameid=i, blob=blob)
            sent.append(i)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.7)
    # Queues hold SNDHWM 1 + RCVHWM 1 + a TCP buffer's worth; the producer
    # must be far from done while nothing drains.
    assert len(sent) < n
    for _ in range(n):
        recv.recv()
    t.join(timeout=10)
    assert len(sent) == n
    recv.close(); pub.close()


def test_pair_channel_duplex_echo():
    prod = PairChannel(WILD, btid=1, bind=True)
    cons = PairChannel(prod.addr, btid=None, bind=False)
    mid = cons.send(shape_params=np.zeros((4, 2), np.float32), shape_ids=[1, 2])
    got = prod.recv(timeoutms=5000)
    assert got is not None and got["btmid"] == mid
    assert got["shape_ids"] == [1, 2]
    prod.send(echo=got["btmid"])
    back = cons.recv(timeoutms=5000)
    assert back["echo"] == mid and back["btid"] == 1
    assert cons.recv(timeoutms=0) is None  # poll-style non-blocking recv
    prod.close(); cons.close()


def test_rpc_req_rep():
    server = RpcServer(WILD)
    client = RpcClient(server.addr, timeoutms=5000)
    result = {}

    def serve():
        req = server.recv(timeoutms=5000)
        result.update(req)
        server.reply(obs=np.zeros(4, np.float32), reward=1.0, done=False)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    rep = client.call(cmd="step", action=0.5)
    t.join(timeout=5)
    assert result["cmd"] == "step" and result["action"] == 0.5
    assert rep["reward"] == 1.0 and rep["done"] is False
    assert rep["obs"].shape == (4,)
    client.close(); server.close()


def test_rpc_client_timeout():
    server = RpcServer(WILD)  # never replies
    client = RpcClient(server.addr, timeoutms=100)
    with pytest.raises(ReceiveTimeoutError):
        client.call(cmd="reset")
    client.close(); server.close()


def test_publish_tracked_bounds_buffer_reuse():
    """publish_tracked returns a MessageTracker that completes once the IO
    thread releases the payload buffers, so a rotating pool can wait on a
    slot before rendering into it again (safe for any consumer count)."""
    import numpy as np

    from blendjax.transport import DataPublisherSocket, DataReceiverSocket

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    recv = DataReceiverSocket([pub.addr], timeoutms=10_000)
    try:
        buf = np.arange(64, dtype=np.uint8).reshape(8, 8)
        tracker = pub.publish_tracked(image=buf, frameid=7)
        msg, _ = recv.recv(copy_arrays=True)
        assert msg["frameid"] == 7
        np.testing.assert_array_equal(msg["image"], buf)
        tracker.wait(timeout=10)  # delivered -> buffers released
        assert tracker.done
    finally:
        recv.close()
        pub.close()


def test_decode_rejects_malformed_frames():
    """Corrupt wire input fails with clear errors, not silent garbage."""
    import pytest as _pytest

    from blendjax.transport.wire import decode_message, encode_message

    frames = encode_message({"a": np.arange(6).reshape(2, 3)})
    # bad magic: not tensor codec, not pickle -> pickle path raises
    bad = [b"XXXX" + bytes(frames[0])[4:], *frames[1:]]
    with _pytest.raises(Exception):
        decode_message(bad)
    # truncated payload frame: frombuffer size mismatch
    truncated = [frames[0], bytes(frames[1])[:-8]]
    with _pytest.raises(ValueError):
        decode_message(truncated)
    # unsupported wire version
    import msgpack

    from blendjax.constants import WIRE_MAGIC

    hdr = WIRE_MAGIC + msgpack.packb([99, []], use_bin_type=True)
    with _pytest.raises(ValueError, match="version"):
        decode_message([hdr])


def test_decode_rejects_pickle_when_disallowed():
    from blendjax.transport.wire import decode_message, encode_message

    frames = encode_message({"x": 1}, codec="pickle")
    import pytest as _pytest

    with _pytest.raises(ValueError, match="pickle"):
        decode_message(frames, allow_pickle=False)


# -- compressed wire frames ("ndz") ------------------------------------------


def test_ndz_roundtrip_and_interleaving():
    """Compressible arrays above the threshold ship as zlib "ndz"
    entries; small or incompressible ones stay raw "nd" — both kinds
    interleave in one message and decode bit-exact."""
    from blendjax.transport.wire import sizeof_frames

    compressible = np.tile(
        np.arange(64, dtype=np.uint8), 4096
    ).reshape(512, 512)
    rng = np.random.default_rng(7)
    incompressible = rng.integers(0, 256, (256, 256), dtype=np.uint8)
    tiny = np.arange(16, dtype=np.float32)
    msg = {
        "img": compressible,
        "noise": incompressible,
        "xy": tiny,
        "frameid": 3,
        "name": "cube",
    }
    plain = encode_message(msg)
    packed = encode_message(msg, compress_level=6, compress_min_bytes=1024)
    assert sizeof_frames(packed) < sizeof_frames(plain) // 2
    # noise frame shipped raw: compression would not have shrunk it
    assert any(
        bytes(a) == incompressible.tobytes() for a in packed[1:]
    )
    out = decode_message(packed)
    np.testing.assert_array_equal(out["img"], compressible)
    np.testing.assert_array_equal(out["noise"], incompressible)
    np.testing.assert_array_equal(out["xy"], tiny)
    assert out["frameid"] == 3 and out["name"] == "cube"


def test_ndz_decodes_with_pickle_disallowed():
    """The compressed path is pickle-free: an untrusted-network consumer
    (allow_pickle=False) accepts "ndz" frames."""
    msg = {"img": np.zeros((256, 256), np.uint8), "frameid": 1}
    frames = encode_message(msg, compress_level=1, compress_min_bytes=1024)
    out = decode_message(frames, allow_pickle=False)
    np.testing.assert_array_equal(out["img"], msg["img"])


def test_ndz_rejects_decompression_bomb_and_truncation():
    """The inflate is bounded by the DECLARED array size (the
    untrusted-network path must not allocate more than an honest raw
    frame could make it hold), and truncated streams fail loudly."""
    import zlib

    import msgpack

    from blendjax.constants import WIRE_MAGIC

    bomb = zlib.compress(b"\x00" * (1 << 20), 9)  # ~1 KB -> 1 MB
    hdr = WIRE_MAGIC + msgpack.packb(
        [1, [["ndz", "x", [4], "|u1", 0]]], use_bin_type=True
    )
    with pytest.raises(ValueError, match="declared"):
        decode_message([hdr, bomb])

    good = encode_message(
        {"x": np.zeros(65536, np.uint8)}, compress_level=1
    )
    with pytest.raises(ValueError, match="declared"):
        decode_message([good[0], bytes(good[1])[:-4]])


def test_ndz_below_threshold_stays_raw():
    msg = {"img": np.zeros((64,), np.uint8)}
    frames = encode_message(msg, compress_level=9, compress_min_bytes=1024)
    assert bytes(frames[1]) == msg["img"].tobytes()


def test_ndz_over_socket_with_compressing_publisher():
    """A compress_level publisher feeds an UNMODIFIED receiver — the
    per-publisher negotiation is one-sided by design."""
    pub = DataPublisherSocket(
        WILD, btid=0, compress_level=6, compress_min_bytes=1024
    )
    recv = DataReceiverSocket([pub.addr], timeoutms=5000)
    img = np.tile(np.arange(256, dtype=np.uint8), 1024).reshape(512, 512)
    pub.publish(image=img, frameid=5)
    msg, raw = recv.recv(copy_arrays=True)
    np.testing.assert_array_equal(msg["image"], img)
    assert msg["frameid"] == 5
    # the wire actually carried the compressed frame
    from blendjax.transport import sizeof_frames

    assert sizeof_frames(raw) < img.nbytes // 2
    recv.close(); pub.close()


def test_sizeof_frames_counts_all_frame_types():
    import array

    from blendjax.transport.wire import sizeof_frames

    arr = np.arange(12, dtype=np.uint8)
    frames = [
        b"0123",                      # bytes
        bytearray(b"456789"),         # bytearray
        memoryview(arr),              # memoryview (nbytes, not len)
        arr.reshape(3, 4).data,       # multi-dim view: len() counts rows
        np.arange(3, dtype=np.int32).data,  # itemsize 4: len() counts items
        array.array("B", [1, 2, 3]),  # other buffer: the bytes() fallback
    ]
    assert sizeof_frames(frames) == 4 + 6 + 12 + 12 + 12 + 3
