"""Transport layer tests: codecs, stream semantics, duplex, RPC.

These are hermetic (no Blender, no GPU): producers are plain Python on the
other end of real TCP sockets, per SURVEY.md §4's "fake producer" strategy.
"""

import pickle
import threading
import time

import numpy as np
import pytest

from blendjax.transport import (
    DataPublisherSocket,
    DataReceiverSocket,
    PairChannel,
    ReceiveTimeoutError,
    RpcClient,
    RpcServer,
    decode_message,
    encode_message,
)

WILD = "tcp://127.0.0.1:*"


def test_tensor_codec_roundtrip():
    msg = {
        "image": np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4),
        "xy": np.ones((5, 2), dtype=np.float32),
        "frameid": 7,
        "name": "cube",
        "nested": {"a": [1, 2, 3], "b": None},
        "weird": {1, 2, 3},  # a set: falls back to embedded pickle
        "npscalar": np.int64(42),
    }
    frames = encode_message(msg, codec="tensor")
    out = decode_message(frames)
    assert out["image"].dtype == np.uint8 and out["image"].shape == (2, 3, 4)
    np.testing.assert_array_equal(out["image"], msg["image"])
    np.testing.assert_array_equal(out["xy"], msg["xy"])
    assert out["frameid"] == 7 and out["name"] == "cube"
    assert out["nested"] == {"a": [1, 2, 3], "b": None}
    assert out["weird"] == {1, 2, 3}
    assert out["npscalar"] == 42


def test_tensor_codec_zero_size_array():
    msg = {"empty": np.zeros((0, 4), dtype=np.float32)}
    out = decode_message(encode_message(msg, codec="tensor"))
    assert out["empty"].shape == (0, 4)


def test_pickle_codec_autodetect():
    msg = {"image": np.zeros((4, 4), np.uint8), "btid": 3}
    frames = encode_message(msg, codec="pickle")
    assert len(frames) == 1
    out = decode_message(frames)
    np.testing.assert_array_equal(out["image"], msg["image"])
    assert out["btid"] == 3


def test_push_pull_stream_and_fan_in():
    pub_a = DataPublisherSocket(WILD, btid=0)
    pub_b = DataPublisherSocket(WILD, btid=1)
    recv = DataReceiverSocket([pub_a.addr, pub_b.addr], timeoutms=5000)
    img = np.random.randint(0, 255, (8, 8, 4), dtype=np.uint8)
    for i in range(4):
        pub_a.publish(image=img, frameid=i)
        pub_b.publish(image=img, frameid=i)
    seen = set()
    for _ in range(8):
        msg, raw = recv.recv()
        assert msg["image"].shape == (8, 8, 4)
        seen.add((msg["btid"], msg["frameid"]))
    assert seen == {(b, i) for b in (0, 1) for i in range(4)}
    recv.close(); pub_a.close(); pub_b.close()


def test_receiver_timeout_raises():
    pub = DataPublisherSocket(WILD, btid=0)
    recv = DataReceiverSocket([pub.addr], timeoutms=50)
    with pytest.raises(ReceiveTimeoutError):
        recv.recv()
    recv.close(); pub.close()


def test_legacy_pickle_producer_interop():
    """An unmodified btb-style producer (send_pyobj) feeds our receiver."""
    import zmq

    from blendjax.transport.channels import zmq_context

    sock = zmq_context().socket(zmq.PUSH)
    sock.setsockopt(zmq.SNDHWM, 10)
    sock.setsockopt(zmq.IMMEDIATE, 1)
    sock.bind(WILD)
    addr = sock.getsockopt_string(zmq.LAST_ENDPOINT)
    recv = DataReceiverSocket([addr], timeoutms=5000)
    payload = {"btid": 9, "image": np.ones((2, 2), np.uint8), "frameid": 0}
    sock.send(pickle.dumps(payload, protocol=3))  # exactly what send_pyobj does
    msg, _ = recv.recv()
    assert msg["btid"] == 9
    np.testing.assert_array_equal(msg["image"], payload["image"])
    recv.close(); sock.close(0)


def test_backpressure_hwm_blocks_producer():
    """With no consumer draining, a small HWM must block the producer
    (reference behavior: Blender blocks when consumers are slow,
    ``examples/datagen/Readme.md:168-175``)."""
    pub = DataPublisherSocket(WILD, btid=0, send_hwm=1)
    recv = DataReceiverSocket([pub.addr], queue_size=1, timeoutms=5000)
    # Give the connection a moment to establish so IMMEDIATE doesn't drop.
    time.sleep(0.2)
    # Payloads must dwarf kernel TCP buffers; HWM counts messages, the OS
    # buffer absorbs bytes.
    blob = np.zeros(4 * 1024 * 1024, dtype=np.uint8)
    n = 12
    sent = []

    def producer():
        for i in range(n):
            pub.publish(frameid=i, blob=blob)
            sent.append(i)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.7)
    # Queues hold SNDHWM 1 + RCVHWM 1 + a TCP buffer's worth; the producer
    # must be far from done while nothing drains.
    assert len(sent) < n
    for _ in range(n):
        recv.recv()
    t.join(timeout=10)
    assert len(sent) == n
    recv.close(); pub.close()


def test_pair_channel_duplex_echo():
    prod = PairChannel(WILD, btid=1, bind=True)
    cons = PairChannel(prod.addr, btid=None, bind=False)
    mid = cons.send(shape_params=np.zeros((4, 2), np.float32), shape_ids=[1, 2])
    got = prod.recv(timeoutms=5000)
    assert got is not None and got["btmid"] == mid
    assert got["shape_ids"] == [1, 2]
    prod.send(echo=got["btmid"])
    back = cons.recv(timeoutms=5000)
    assert back["echo"] == mid and back["btid"] == 1
    assert cons.recv(timeoutms=0) is None  # poll-style non-blocking recv
    prod.close(); cons.close()


def test_rpc_req_rep():
    server = RpcServer(WILD)
    client = RpcClient(server.addr, timeoutms=5000)
    result = {}

    def serve():
        req = server.recv(timeoutms=5000)
        result.update(req)
        server.reply(obs=np.zeros(4, np.float32), reward=1.0, done=False)

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    rep = client.call(cmd="step", action=0.5)
    t.join(timeout=5)
    assert result["cmd"] == "step" and result["action"] == 0.5
    assert rep["reward"] == 1.0 and rep["done"] is False
    assert rep["obs"].shape == (4,)
    client.close(); server.close()


def test_rpc_client_timeout():
    server = RpcServer(WILD)  # never replies
    client = RpcClient(server.addr, timeoutms=100)
    with pytest.raises(ReceiveTimeoutError):
        client.call(cmd="reset")
    client.close(); server.close()


def test_publish_tracked_bounds_buffer_reuse():
    """publish_tracked returns a MessageTracker that completes once the IO
    thread releases the payload buffers, so a rotating pool can wait on a
    slot before rendering into it again (safe for any consumer count)."""
    import numpy as np

    from blendjax.transport import DataPublisherSocket, DataReceiverSocket

    pub = DataPublisherSocket("tcp://127.0.0.1:*", btid=0)
    recv = DataReceiverSocket([pub.addr], timeoutms=10_000)
    try:
        buf = np.arange(64, dtype=np.uint8).reshape(8, 8)
        tracker = pub.publish_tracked(image=buf, frameid=7)
        msg, _ = recv.recv(copy_arrays=True)
        assert msg["frameid"] == 7
        np.testing.assert_array_equal(msg["image"], buf)
        tracker.wait(timeout=10)  # delivered -> buffers released
        assert tracker.done
    finally:
        recv.close()
        pub.close()


def test_decode_rejects_malformed_frames():
    """Corrupt wire input fails with clear errors, not silent garbage."""
    import pytest as _pytest

    from blendjax.transport.wire import decode_message, encode_message

    frames = encode_message({"a": np.arange(6).reshape(2, 3)})
    # bad magic: not tensor codec, not pickle -> pickle path raises
    bad = [b"XXXX" + bytes(frames[0])[4:], *frames[1:]]
    with _pytest.raises(Exception):
        decode_message(bad)
    # truncated payload frame: frombuffer size mismatch
    truncated = [frames[0], bytes(frames[1])[:-8]]
    with _pytest.raises(ValueError):
        decode_message(truncated)
    # unsupported wire version
    import msgpack

    from blendjax.constants import WIRE_MAGIC

    hdr = WIRE_MAGIC + msgpack.packb([99, []], use_bin_type=True)
    with _pytest.raises(ValueError, match="version"):
        decode_message([hdr])


def test_decode_rejects_pickle_when_disallowed():
    from blendjax.transport.wire import decode_message, encode_message

    frames = encode_message({"x": 1}, codec="pickle")
    import pytest as _pytest

    with _pytest.raises(ValueError, match="pickle"):
        decode_message(frames, allow_pickle=False)


# -- compressed wire frames ("ndz") ------------------------------------------


def test_ndz_roundtrip_and_interleaving():
    """Compressible arrays above the threshold ship as zlib "ndz"
    entries; small or incompressible ones stay raw "nd" — both kinds
    interleave in one message and decode bit-exact."""
    from blendjax.transport.wire import sizeof_frames

    compressible = np.tile(
        np.arange(64, dtype=np.uint8), 4096
    ).reshape(512, 512)
    rng = np.random.default_rng(7)
    incompressible = rng.integers(0, 256, (256, 256), dtype=np.uint8)
    tiny = np.arange(16, dtype=np.float32)
    msg = {
        "img": compressible,
        "noise": incompressible,
        "xy": tiny,
        "frameid": 3,
        "name": "cube",
    }
    plain = encode_message(msg)
    packed = encode_message(msg, compress_level=6, compress_min_bytes=1024)
    assert sizeof_frames(packed) < sizeof_frames(plain) // 2
    # noise frame shipped raw: compression would not have shrunk it
    assert any(
        bytes(a) == incompressible.tobytes() for a in packed[1:]
    )
    out = decode_message(packed)
    np.testing.assert_array_equal(out["img"], compressible)
    np.testing.assert_array_equal(out["noise"], incompressible)
    np.testing.assert_array_equal(out["xy"], tiny)
    assert out["frameid"] == 3 and out["name"] == "cube"


def test_ndz_decodes_with_pickle_disallowed():
    """The compressed path is pickle-free: an untrusted-network consumer
    (allow_pickle=False) accepts "ndz" frames."""
    msg = {"img": np.zeros((256, 256), np.uint8), "frameid": 1}
    frames = encode_message(msg, compress_level=1, compress_min_bytes=1024)
    out = decode_message(frames, allow_pickle=False)
    np.testing.assert_array_equal(out["img"], msg["img"])


def test_ndz_rejects_decompression_bomb_and_truncation():
    """The inflate is bounded by the DECLARED array size (the
    untrusted-network path must not allocate more than an honest raw
    frame could make it hold), and truncated streams fail loudly."""
    import zlib

    import msgpack

    from blendjax.constants import WIRE_MAGIC

    bomb = zlib.compress(b"\x00" * (1 << 20), 9)  # ~1 KB -> 1 MB
    hdr = WIRE_MAGIC + msgpack.packb(
        [1, [["ndz", "x", [4], "|u1", 0]]], use_bin_type=True
    )
    with pytest.raises(ValueError, match="declared"):
        decode_message([hdr, bomb])

    good = encode_message(
        {"x": np.zeros(65536, np.uint8)}, compress_level=1
    )
    with pytest.raises(ValueError, match="declared"):
        decode_message([good[0], bytes(good[1])[:-4]])


def test_ndz_below_threshold_stays_raw():
    msg = {"img": np.zeros((64,), np.uint8)}
    frames = encode_message(msg, compress_level=9, compress_min_bytes=1024)
    assert bytes(frames[1]) == msg["img"].tobytes()


def test_ndz_over_socket_with_compressing_publisher():
    """A compress_level publisher feeds an UNMODIFIED receiver — the
    per-publisher negotiation is one-sided by design."""
    pub = DataPublisherSocket(
        WILD, btid=0, compress_level=6, compress_min_bytes=1024
    )
    recv = DataReceiverSocket([pub.addr], timeoutms=5000)
    img = np.tile(np.arange(256, dtype=np.uint8), 1024).reshape(512, 512)
    pub.publish(image=img, frameid=5)
    msg, raw = recv.recv(copy_arrays=True)
    np.testing.assert_array_equal(msg["image"], img)
    assert msg["frameid"] == 5
    # the wire actually carried the compressed frame
    from blendjax.transport import sizeof_frames

    assert sizeof_frames(raw) < img.nbytes // 2
    recv.close(); pub.close()


def test_sizeof_frames_counts_all_frame_types():
    import array

    from blendjax.transport.wire import sizeof_frames

    arr = np.arange(12, dtype=np.uint8)
    frames = [
        b"0123",                      # bytes
        bytearray(b"456789"),         # bytearray
        memoryview(arr),              # memoryview (nbytes, not len)
        arr.reshape(3, 4).data,       # multi-dim view: len() counts rows
        np.arange(3, dtype=np.int32).data,  # itemsize 4: len() counts items
        array.array("B", [1, 2, 3]),  # other buffer: the bytes() fallback
    ]
    assert sizeof_frames(frames) == 4 + 6 + 12 + 12 + 12 + 3


# -- run-length wire frames ("ndr") ------------------------------------------


def _runny(shape, value=7, box=((10, 30), (10, 30))):
    """Background-dominated uint8 frames — the ndr-winning content."""
    img = np.zeros(shape, np.uint8)
    (y0, y1), (x0, x1) = box
    img[..., y0:y1, x0:x1, :] = value
    return img


def test_ndr_roundtrip_and_three_kind_interleave():
    """'ndr' interleaves with 'ndz' and 'nd' inside ONE message: the
    run-heavy frame ships run-packed, the compressible-but-not-runny
    field ships zlib, incompressible noise stays raw — and everything
    decodes bit-exact."""
    from blendjax.transport.wire import sizeof_frames

    img = _runny((8, 64, 64, 4))
    ramp = np.tile(np.arange(64, dtype=np.uint8), 2048).reshape(512, 256)
    rng = np.random.default_rng(3)
    noise = rng.integers(0, 256, (256, 256), dtype=np.uint8)
    msg = {"image": img, "ramp": ramp, "noise": noise, "frameid": 9}
    frames = encode_message(
        msg, compress_rle=True, compress_level=6, compress_min_bytes=1024
    )
    # compressed total ~= the raw noise frame plus small packed frames
    assert sizeof_frames(frames) < noise.nbytes + (
        img.nbytes + ramp.nbytes
    ) // 16
    # the noise frame crossed raw
    assert any(bytes(f) == noise.tobytes() for f in frames[1:])
    out = decode_message(frames, allow_pickle=False)  # pickle-free path
    np.testing.assert_array_equal(out["image"], img)
    np.testing.assert_array_equal(out["ramp"], ramp)
    np.testing.assert_array_equal(out["noise"], noise)
    assert out["frameid"] == 9


def test_ndr_rejects_zero_byte_truncated_and_padded_frames():
    """The ndz hostile-stream guards carried over: declared-zero-byte
    refusal, a wire buffer that doesn't match the declared capacity,
    and run planes that under-declare the row item count all fail
    loudly — allocation stays bounded by the declared shape."""
    import msgpack

    from blendjax.constants import WIRE_MAGIC

    def hdr(entry):
        return WIRE_MAGIC + msgpack.packb([1, [entry]], use_bin_type=True)

    # zero-byte declaration
    with pytest.raises(ValueError, match="zero bytes"):
        decode_message(
            [hdr(["ndr", "x", [0, 4], "|u1", 0, 4, 1]), b""]
        )
    # truncated buffer (wrong size for rows x stride)
    good = encode_message(
        {"x": _runny((4, 64, 64, 4))}, compress_rle=True,
        compress_min_bytes=1024,
    )
    with pytest.raises(ValueError, match="truncated or padded"):
        decode_message([good[0], bytes(good[1])[:-8]])
    # run planes under-declaring the row: runs sum != items
    frames = encode_message(
        {"x": _runny((4, 64, 64, 4))}, compress_rle=True,
        compress_min_bytes=1024,
    )
    buf = np.frombuffer(bytes(frames[1]), np.uint8).copy()
    buf[-1] = 0
    buf[-2] = 0  # zero a run's hi/lo bytes
    stride = buf.size // 4
    lo = stride - 2 * (stride // 6)  # cap*(isz+2): isz=4 -> lo plane at 2/3
    buf2 = buf.reshape(4, stride).copy()
    buf2[:, lo:] = 0  # wipe every run plane entirely
    with pytest.raises(ValueError, match="declared"):
        decode_message([frames[0], buf2.tobytes()])
    # non-uint8 declaration refused outright
    with pytest.raises(ValueError, match="uint8-only"):
        decode_message(
            [hdr(["ndr", "x", [2, 8], "<f4", 0, 4, 1]), b"\x00" * 24]
        )


def test_ndr_incompressible_and_small_frames_stay_raw():
    rng = np.random.default_rng(5)
    noise = rng.integers(0, 256, (64, 1024), dtype=np.uint8)
    frames = encode_message(
        {"noise": noise}, compress_rle=True, compress_min_bytes=1024
    )
    assert bytes(frames[1]) == noise.tobytes()
    tiny = np.zeros((64,), np.uint8)
    frames = encode_message(
        {"tiny": tiny}, compress_rle=True, compress_min_bytes=1024
    )
    assert bytes(frames[1]) == tiny.tobytes()


def test_ndr_pinned_cap_overflow_falls_back_and_sticky_cap_ratchets():
    from blendjax.transport.wire import WireCompressState

    rng = np.random.default_rng(0)
    busy = rng.integers(0, 4, (4, 4096), dtype=np.uint8)  # many short runs
    # pinned cap too small: the frame ships raw for THIS message
    frames = encode_message(
        {"x": busy}, compress_rle=True, rle_cap=8, compress_min_bytes=1024
    )
    assert bytes(frames[1]) == busy.tobytes()
    # sticky state: a quiet frame sets a small cap, a busier one
    # ratchets it up instead of failing
    state = WireCompressState()
    quiet = _runny((4, 64, 64, 4), box=((8, 12), (8, 12)))
    encode_message(
        {"x": quiet}, compress_rle=True, compress_min_bytes=1024,
        state=state,
    )
    cap_quiet = state.rle_cap("x")
    busier = _runny((4, 64, 64, 4), box=((4, 60), (4, 60)), value=1)
    busier[:, ::2, ::2, :] = 2  # checkerboard inside the box
    frames = encode_message(
        {"x": busier}, compress_rle=True, compress_min_bytes=1024,
        state=state,
    )
    out = decode_message(frames)
    np.testing.assert_array_equal(out["x"], busier)
    assert state.rle_cap("x") >= cap_quiet


def test_ndr_defers_only_for_prebatched_messages():
    from blendjax.ops.tiles import rle_expand_packed_np

    img = _runny((8, 64, 64, 4))
    stamped = encode_message(
        {"_prebatched": True, "image": img}, compress_rle=True,
        compress_min_bytes=1024,
    )
    out = decode_message(stamped, defer_rle=True)
    assert "image" not in out
    shape, isz, cap = out["image__ndrspec"]
    np.testing.assert_array_equal(
        rle_expand_packed_np(out["image__ndr"], shape, isz, cap), img
    )
    plain = encode_message(
        {"image": img}, compress_rle=True, compress_min_bytes=1024
    )
    out = decode_message(plain, defer_rle=True)
    np.testing.assert_array_equal(out["image"], img)
    assert "image__ndr" not in out


def test_ndr_over_socket_with_rle_publisher():
    pub = DataPublisherSocket(
        WILD, btid=0, compress_rle=True, compress_min_bytes=1024
    )
    recv = DataReceiverSocket([pub.addr], timeoutms=5000)
    img = _runny((4, 64, 64, 4))
    pub.publish(image=img, frameid=5)
    msg, raw = recv.recv(copy_arrays=True)
    np.testing.assert_array_equal(msg["image"], img)
    assert msg["frameid"] == 5
    from blendjax.transport import sizeof_frames

    assert sizeof_frames(raw) < img.nbytes // 4
    recv.close(); pub.close()


def test_ndr_replay_round_trip(tmp_path):
    """Recorded raw wire frames with 'ndr' entries replay bit-exact
    through ReplayStream (which always host-inflates)."""
    from blendjax.data.replay import FileRecorder, ReplayStream

    img = _runny((4, 64, 64, 4))
    path = str(tmp_path / "wire.bjr")
    with FileRecorder(path) as rec:
        for i in range(3):
            rec.save(encode_message(
                {"_prebatched": True, "image": img + i, "frameid": i},
                compress_rle=True, compress_min_bytes=1024,
            ))
    got = list(ReplayStream(path))
    assert len(got) == 3
    for i, msg in enumerate(got):
        np.testing.assert_array_equal(msg["image"], img + i)
        assert msg["frameid"] == i


def test_quantize_f16_exact_for_pixel_coords_and_bounded_otherwise():
    """Wire f16 quantization of float sidecars: integer pixel
    coordinates (the point-label payload) survive EXACTLY up to 2048;
    arbitrary floats stay within f16's relative error bound."""
    coords = np.arange(0, 2048, dtype=np.float32).reshape(-1, 2)
    frames = encode_message({"xy": coords}, quantize_f16=("xy",))
    out = decode_message(frames)
    assert out["xy"].dtype == np.float16
    np.testing.assert_array_equal(out["xy"].astype(np.float32), coords)
    rng = np.random.default_rng(1)
    vals = (rng.random(1024, dtype=np.float32) * 100.0).reshape(-1, 2)
    out = decode_message(
        encode_message({"xy": vals}, quantize_f16=("xy",))
    )
    rel = np.abs(out["xy"].astype(np.float32) - vals) / np.abs(vals)
    assert float(np.nanmax(rel)) <= 2 ** -10  # half-precision ulp bound
    # non-float and unnamed fields are untouched
    ids = np.arange(8, dtype=np.int64)
    out = decode_message(
        encode_message({"xy": vals, "ids": ids}, quantize_f16=("ids",))
    )
    assert out["xy"].dtype == np.float32
    assert out["ids"].dtype == np.int64


def test_compress_state_skip_memo_and_recovery():
    """Satellite: a field that LOSES the size check stops paying the
    trial compression for SKIP_FRAMES encodes, then re-tries — so an
    incompressible stream stops burning CPU while one that turns
    compressible recovers."""
    from blendjax.transport.wire import WireCompressState

    state = WireCompressState()
    rng = np.random.default_rng(2)
    noise = rng.integers(0, 256, (64, 1024), dtype=np.uint8)
    encode_message(
        {"x": noise}, compress_level=6, compress_min_bytes=1024,
        state=state,
    )
    assert state._skip[("z", "x")] == state.SKIP_FRAMES
    before = state._skip[("z", "x")]
    encode_message(
        {"x": noise}, compress_level=6, compress_min_bytes=1024,
        state=state,
    )
    assert state._skip[("z", "x")] == before - 1  # trial skipped
    # drain the window with compressible content: the first re-trial
    # WINS and clears the memo
    ramp = np.tile(np.arange(64, dtype=np.uint8), 1024)
    for _ in range(state.SKIP_FRAMES):
        encode_message(
            {"x": ramp}, compress_level=6, compress_min_bytes=1024,
            state=state,
        )
    frames = encode_message(
        {"x": ramp}, compress_level=6, compress_min_bytes=1024,
        state=state,
    )
    assert ("z", "x") not in state._skip
    out = decode_message(frames)
    np.testing.assert_array_equal(out["x"], ramp)


def test_parallel_inflate_pool_decodes_multi_ndz_messages():
    from concurrent.futures import ThreadPoolExecutor

    a = np.tile(np.arange(64, dtype=np.uint8), 8192)
    b = np.tile(np.arange(32, dtype=np.uint8), 16384).reshape(64, -1)
    frames = encode_message(
        {"a": a, "b": b}, compress_level=6, compress_min_bytes=1024
    )
    with ThreadPoolExecutor(2) as pool:
        out = decode_message(frames, inflate_pool=pool)
    np.testing.assert_array_equal(out["a"], a)
    np.testing.assert_array_equal(out["b"], b)
    # hostile content still refused through the pool path
    bad = [frames[0], bytes(frames[1])[:-4], frames[2]]
    with ThreadPoolExecutor(2) as pool:
        with pytest.raises(ValueError, match="declared"):
            decode_message(bad, inflate_pool=pool)
